"""Fleet serving tier: replicated engines behind a prefix-affinity router.

Everything below this module — sharded decode, paged prefix restore, SLO
scheduling, supervised recovery — serves from ONE
:class:`~unionml_tpu.serving.continuous.DecodeEngine` on one mesh.
:class:`EngineFleet` is the scale-out layer (ROADMAP item 2): N supervised
replicas, each a ``ContinuousBatcher`` + ``DecodeEngine`` +
``EngineSupervisor`` on its own device subset (see :func:`split_mesh`),
behind a :class:`Router` that picks a replica per request by:

- **Radix-prefix affinity.** The router digests the block-aligned prompt
  prefix with the SAME hashing as the engines' radix prefix cache
  (:func:`~unionml_tpu.serving.prefix_cache.prefix_digests`, chained over
  :func:`~unionml_tpu.serving.prefix_cache.block_key`) and keeps a bounded
  recent-prefix digest index per replica; a prompt routes to the replica
  whose cache most likely holds its longest prefix, so shared system prompts
  and chat histories restore instead of re-prefilling on a random replica.
- **Session stickiness.** Multi-turn chat pins a ``session_id`` to its
  replica (TTL-evicted map), keeping every turn's growing transcript against
  the cache that already holds it; a dead/unroutable replica falls back to
  the affinity winner and the session RE-STICKS there.
- **Load + health.** Per-replica queue depth, slot occupancy, and the
  scheduler's queue-wait EMA (:meth:`SLOScheduler.load_signal`) down-rank
  busy replicas; supervisor state gates hard — ``rebuilding``/``failed``
  replicas get zero weight, ``degraded`` is down-weighted.

The score for a healthy replica ``i`` is::

    score_i = weight_i * (1 + affinity_weight * hit_frac_i)
                       / (1 + load_weight * load_i)

with ``weight_i`` 1.0 (``ok``) or ``degraded_weight``, ``hit_frac_i`` the
digest-matched fraction of the prompt's full blocks, and ``load_i`` the
replica's ``(queued + active) / slots + queue_wait_ema_s``. Ties break to
the less-loaded, then lower-indexed replica.

Failure composes with the supervised-recovery layer instead of bypassing it:
fleet-level shedding applies the PR-5 error contract (429/503 with
Retry-After) at the router BEFORE any replica queue is touched, and a
replica whose rebuild budget exhausts hands its salvageable tickets to the
fleet (``ContinuousBatcher.on_tickets_orphaned``), which RE-ROUTES them to
surviving replicas as resume tickets — transcript-as-prompt, unspent budget,
deadline/priority/sink intact — so an engine death loses zero recoverable
requests fleet-wide.

Lock discipline (graftlint-checked): the router's lock is a LEAF —
``Router`` methods take no other lock, and the fleet never holds its own
counter lock while calling into a replica's batcher or scheduler. Candidate
health/load snapshots are gathered from supervisor/scheduler locks BEFORE
``Router._lock`` is acquired, so no ``supervisor._lock -> router._lock``
ordering exists in either direction.
"""

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from unionml_tpu._logging import logger
from unionml_tpu.serving.continuous import ContinuousBatcher
from unionml_tpu.serving.faults import EngineFailure
from unionml_tpu.serving.prefix_cache import prefix_digests
from unionml_tpu.serving.scheduler import (
    QueueFullError,
    SchedulerConfig,
    SLOScheduler,
)
from unionml_tpu.serving.supervisor import EngineSupervisor

__all__ = ["EngineFleet", "FleetConfig", "Router", "split_mesh"]

# lock-order: Router._lock < (nothing) — router lock is a leaf by design
ROUTE_POLICIES = ("affinity", "random", "round_robin")


class FleetConfig:
    """Knobs for :class:`EngineFleet` + :class:`Router`.

    :param policy: ``affinity`` (scored; the default), ``random`` (seeded
        uniform over healthy replicas — the A/B baseline), or
        ``round_robin``.
    :param max_queue: fleet-level admission bound — total queued requests
        across every replica at which the router sheds with 429 BEFORE
        touching any replica queue (each replica's own scheduler bound still
        applies underneath).
    :param retry_after_s: Retry-After hint attached to router-level sheds.
    :param session_ttl_s: idle time after which a session→replica sticky
        mapping is evicted (the next turn re-routes by affinity).
    :param max_sessions: sticky-map capacity; least-recently-routed sessions
        are evicted first.
    :param affinity_index_blocks: per-replica digest-index capacity (LRU) —
        how many recent block-prefixes the router remembers per replica.
    :param affinity_weight: how strongly a digest match attracts (0 disables
        affinity scoring without disabling measurement).
    :param load_weight: how strongly queue depth/occupancy/wait repel.
    :param degraded_weight: score multiplier for ``degraded`` replicas.
    :param seed: seeds the ``random`` policy's RNG (deterministic A/B runs).
    """

    def __init__(
        self,
        *,
        policy: str = "affinity",
        max_queue: int = 512,
        retry_after_s: float = 1.0,
        session_ttl_s: float = 300.0,
        max_sessions: int = 4096,
        affinity_index_blocks: int = 1024,
        affinity_weight: float = 1.0,
        load_weight: float = 1.0,
        degraded_weight: float = 0.5,
        seed: int = 0,
    ) -> None:
        if policy not in ROUTE_POLICIES:
            raise ValueError(f"policy must be one of {ROUTE_POLICIES}, got {policy!r}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.policy = policy
        self.max_queue = int(max_queue)
        self.retry_after_s = float(retry_after_s)
        self.session_ttl_s = float(session_ttl_s)
        self.max_sessions = int(max_sessions)
        self.affinity_index_blocks = int(affinity_index_blocks)
        self.affinity_weight = float(affinity_weight)
        self.load_weight = float(load_weight)
        self.degraded_weight = float(degraded_weight)
        self.seed = int(seed)


class Router:
    """Replica choice: prefix affinity + session stickiness + load/health.

    Pure host bookkeeping — no jax, no engine references. The fleet snapshots
    candidate ``(index, weight, load)`` triples from supervisor/scheduler
    state FIRST and passes them in, so this class's lock nests inside nothing
    and nothing nests inside it (see the module docstring's lock discipline).

    :param num_replicas: fleet size (digest indexes are per-replica).
    :param block_size: the engines' prefix-cache block size — digesting with
        any other granularity would diverge from the radix trees.
    :param config: see :class:`FleetConfig`.
    :param time_fn: injectable clock for TTL tests.
    """

    def __init__(
        self,
        num_replicas: int,
        *,
        block_size: int,
        config: Optional[FleetConfig] = None,
        time_fn: Callable[[], float] = time.monotonic,
    ) -> None:
        import random

        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1, got {num_replicas}")
        self.config = config or FleetConfig()
        self.num_replicas = int(num_replicas)
        self.block_size = int(block_size)
        self._time = time_fn
        self._rng = random.Random(self.config.seed)
        self._lock = threading.Lock()  # lock-leaf (see the module-level lock-order note)
        #: per-replica recent-prefix digest index (insertion-ordered dict as
        #: LRU: re-recording moves to the back, eviction pops the front)
        self._digests: List[Dict[int, None]] = [{} for _ in range(num_replicas)]  # guarded-by: _lock
        #: session_id -> (replica index, last-routed stamp)
        self._sessions: Dict[str, Tuple[int, float]] = {}  # guarded-by: _lock
        self._rr_next = 0  # guarded-by: _lock
        # counters (the /stats generation.fleet.router block) — guarded-by: _lock
        self.lookups = 0  # guarded-by: _lock
        self.lookup_blocks = 0  # guarded-by: _lock
        self.hit_blocks = 0  # guarded-by: _lock
        self.prefix_hits = 0  # guarded-by: _lock
        self.sticky_routes = 0  # guarded-by: _lock
        self.affinity_routes = 0  # guarded-by: _lock
        self.random_routes = 0  # guarded-by: _lock
        self.round_robin_routes = 0  # guarded-by: _lock
        self.dead_session_fallbacks = 0  # guarded-by: _lock
        self.sessions_evicted = 0  # guarded-by: _lock

    # ------------------------------------------------------------------ route

    def route(
        self,
        tokens: Sequence[int],
        candidates: Sequence[Tuple[int, float, float]],
        session_id: Optional[str] = None,
    ) -> Tuple[int, Dict[str, Any]]:
        """Pick a replica for ``tokens`` among healthy ``candidates``.

        ``candidates`` are ``(index, weight, load)`` triples the fleet
        snapshots WITHOUT holding this router's lock — ``weight`` already
        encodes supervisor health (0-weight replicas must not be passed at
        all), ``load`` the replica's occupancy + queue-wait signal. Returns
        ``(index, decision)`` where ``decision`` records how the choice was
        made (``sticky``/``affinity``/``random``/``round_robin``) and the
        digest-matched block count on the CHOSEN replica — the router-level
        prefix-hit measurement both policies share, so an A/B compares like
        with like. Records the prompt's digests on the winner (it will hold
        these blocks once the request prefills) and re-sticks the session.
        """
        if not candidates:
            raise ValueError("route() needs at least one healthy candidate")
        digests = prefix_digests(tokens, self.block_size)
        now = self._time()
        with self._lock:
            self.lookups += 1
            self._expire_sessions(now)
            alive = {int(idx) for idx, _, _ in candidates}
            chosen: Optional[int] = None
            how = self.config.policy
            if session_id is not None:
                entry = self._sessions.get(session_id)
                if entry is not None:
                    if entry[0] in alive:
                        chosen, how = entry[0], "sticky"
                    else:
                        # sticky replica died or is rebuilding: fall back to
                        # the scored choice below and re-stick there
                        self.dead_session_fallbacks += 1
            if chosen is None:
                if self.config.policy == "random":
                    chosen = int(candidates[self._rng.randrange(len(candidates))][0])
                elif self.config.policy == "round_robin":
                    order = sorted(alive)
                    chosen = order[self._rr_next % len(order)]
                    self._rr_next += 1
                else:
                    chosen = self._best(digests, candidates)
            matched = self._matched_blocks(chosen, digests)
            self.lookup_blocks += len(digests)
            self.hit_blocks += matched
            if matched > 0:
                self.prefix_hits += 1
            counter = {
                "sticky": "sticky_routes",
                "affinity": "affinity_routes",
                "random": "random_routes",
                "round_robin": "round_robin_routes",
            }[how]
            setattr(self, counter, getattr(self, counter) + 1)
            self._record(chosen, digests)
            if session_id is not None:
                self._sessions.pop(session_id, None)
                self._sessions[session_id] = (chosen, now)
                while len(self._sessions) > self.config.max_sessions:
                    self._sessions.pop(next(iter(self._sessions)))
                    self.sessions_evicted += 1
            return chosen, {
                "decision": how,
                "matched_blocks": matched,
                "digest_blocks": len(digests),
            }

    def _best(
        self, digests: Sequence[int], candidates: Sequence[Tuple[int, float, float]]
    ) -> int:
        best_idx, best_key = -1, None
        for idx, weight, load in candidates:
            idx = int(idx)
            if digests:
                frac = self._matched_blocks(idx, digests) / len(digests)
            else:
                frac = 0.0
            score = (
                float(weight)
                * (1.0 + self.config.affinity_weight * frac)
                / (1.0 + self.config.load_weight * max(0.0, float(load)))
            )
            key = (-score, float(load), idx)
            if best_key is None or key < best_key:
                best_idx, best_key = idx, key
        return best_idx

    def _matched_blocks(self, index: int, digests: Sequence[int]) -> int:
        # digests are chained, so membership of digests[i] implies the whole
        # prefix through block i was recorded here; walk forward (an LRU
        # eviction of an early digest conservatively truncates the match)
        held = self._digests[index]  # graftlint: disable=data-race -- route() is the only caller and already holds _lock
        matched = 0
        for digest in digests:
            if digest not in held:
                break
            matched += 1
        return matched

    def _record(self, index: int, digests: Sequence[int]) -> None:
        held = self._digests[index]  # graftlint: disable=data-race -- route() is the only caller and already holds _lock
        for digest in digests:
            held.pop(digest, None)
            held[digest] = None
        cap = self.config.affinity_index_blocks
        while len(held) > cap:
            held.pop(next(iter(held)))

    def _expire_sessions(self, now: float) -> None:
        # guarded-by: _lock (route-time sweep; the map is bounded, sessions
        # are insertion-ordered by last route, so expired ones sit in front)
        ttl = self.config.session_ttl_s
        while self._sessions:  # graftlint: disable=data-race -- route() is the only caller and already holds _lock
            sid = next(iter(self._sessions))
            if now - self._sessions[sid][1] <= ttl:
                break
            self._sessions.pop(sid)  # graftlint: disable=lock-discipline -- route() is the only caller and already holds _lock
            self.sessions_evicted += 1  # graftlint: disable=lock-discipline -- route() is the only caller and already holds _lock

    # ------------------------------------------------------------- lifecycle

    def on_replica_rebuilding(self, index: int) -> None:
        """The replica's engine is being rebuilt: its block pool (and so its
        radix cache) will come back empty — forget its digests so affinity
        stops preferring a cache that no longer exists. Sessions stay stuck
        (the replica usually returns); route() excludes it meanwhile."""
        with self._lock:
            self._digests[index].clear()

    def on_replica_failed(self, index: int) -> None:
        """The replica is dead for good (rebuild budget exhausted): drop its
        digests AND its sessions, so every affected session's next turn
        re-routes by affinity — typically to the survivor that adopted the
        session's re-routed transcript."""
        with self._lock:
            self._digests[index].clear()
            for sid in [s for s, (r, _) in self._sessions.items() if r == index]:
                self._sessions.pop(sid)

    def session_replica(self, session_id: str) -> Optional[int]:
        """The replica a session is currently stuck to (None when unmapped)."""
        with self._lock:
            entry = self._sessions.get(session_id)
            return None if entry is None else entry[0]

    # ------------------------------------------------------ autoscaler warm-up

    def hot_digests(self, k: int = 128) -> List[int]:
        """The fleet's ``k`` hottest prefix digests, most recent first —
        drawn round-robin from the tail of every replica's LRU index (the
        tail IS recency). The autoscaler feeds these to
        :meth:`warm_replica` so a scaled-up replica starts with the radix
        paths traffic is actually hitting instead of a cold index that
        repels every affinity score."""
        if k < 1:
            return []
        with self._lock:
            tails = [list(reversed(held)) for held in self._digests if held]
            out: List[int] = []
            seen = set()
            for rank in range(max((len(t) for t in tails), default=0)):
                for tail in tails:
                    if rank < len(tail) and tail[rank] not in seen:
                        seen.add(tail[rank])
                        out.append(tail[rank])
                        if len(out) >= k:
                            return out
            return out

    def warm_replica(self, index: int, digests: Sequence[int]) -> None:
        """Seed ``index``'s digest index (scale-up warm-up): recorded
        oldest-first so the hottest digest (``digests[0]``, per
        :meth:`hot_digests` ordering) lands most-recent in the LRU. The
        replica's radix cache is still cold — the first routed request per
        prefix pays one prefill, after which the advertised affinity is
        real; without seeding, a cold index repels exactly the traffic that
        would warm it."""
        with self._lock:
            self._record(int(index), list(reversed(list(digests))))

    def stats(self) -> Dict[str, Any]:
        """The ``/stats`` → ``generation.fleet.router`` block."""
        with self._lock:
            return {
                "policy": self.config.policy,
                "lookups": self.lookups,
                "lookup_blocks": self.lookup_blocks,
                "hit_blocks": self.hit_blocks,
                "prefix_hits": self.prefix_hits,
                "prefix_hit_rate": (
                    None if self.lookup_blocks == 0
                    else round(self.hit_blocks / self.lookup_blocks, 4)
                ),
                "sticky_routes": self.sticky_routes,
                "affinity_routes": self.affinity_routes,
                "random_routes": self.random_routes,
                "round_robin_routes": self.round_robin_routes,
                "dead_session_fallbacks": self.dead_session_fallbacks,
                "sessions_active": len(self._sessions),
                "sessions_evicted": self.sessions_evicted,
                "indexed_blocks": [len(d) for d in self._digests],
            }


def split_mesh(mesh: Any, n: int) -> List[Any]:
    """Split a mesh's devices into ``n`` equal contiguous sub-meshes.

    Each sub-mesh keeps the parent's axis names with the FIRST axis whose
    size ``n`` divides shrunk by that factor — an 8-device ``{data:2,
    tensor:4}`` mesh splits into two ``{data:1, tensor:4}`` replicas, a
    ``{tensor: 8}`` mesh into two ``{tensor: 4}``. Contiguous grouping keeps
    each replica's collectives on ICI-adjacent chips.
    """
    from unionml_tpu.parallel import make_mesh

    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    devices = list(np.asarray(mesh.devices).flat)
    if len(devices) % n != 0:
        raise ValueError(f"cannot split {len(devices)} devices into {n} equal groups")
    axes = dict(zip(mesh.axis_names, np.asarray(mesh.devices).shape))
    for name, size in axes.items():
        if size % n == 0:
            axes[name] = size // n
            break
    else:
        raise ValueError(f"no axis of {axes} is divisible by {n}")
    per = len(devices) // n
    return [
        make_mesh(axes, devices=devices[i * per : (i + 1) * per]) for i in range(n)
    ]


class _Replica:
    """One fleet member: engine + batcher + supervisor, index-stamped."""

    __slots__ = ("index", "engine", "batcher", "supervisor")

    def __init__(self, index: int, engine: Any, batcher: Any, supervisor: Any) -> None:
        self.index = index
        self.engine = engine
        self.batcher = batcher
        self.supervisor = supervisor


class EngineFleet:
    """N supervised engine replicas behind a :class:`Router`.

    :param engines: the replicas' :class:`DecodeEngine`\\ s (typically built
        on :func:`split_mesh` sub-meshes). Each gets its OWN
        ``ContinuousBatcher`` + ``SLOScheduler`` + ``EngineSupervisor``.
    :param config: router/shedding knobs (:class:`FleetConfig`).
    :param lookahead: per-replica batcher dispatch-ahead depth.
    :param scheduler: a ``SchedulerConfig`` applied to every replica's own
        scheduler (an ``SLOScheduler`` INSTANCE is rejected: replicas must
        not share a queue).
    :param supervisors: optional pre-built supervisors, one per engine
        (tests inject fault-tuned ones); defaults to fresh supervisors.

    The fleet exposes the same async ``generate``/``stream`` surface as a
    single ``ContinuousBatcher`` (plus ``session_id=``), so
    ``build_aiohttp_app`` serves either transparently; ``is_fleet`` lets the
    HTTP layer pick the fleet-shaped ``/healthz`` and ``/stats`` bodies.
    """

    is_fleet = True
    #: the HTTP layer may forward its request_id (trace continuity end-to-end)
    accepts_request_id = True

    def __init__(
        self,
        engines: Sequence[Any],
        *,
        config: Optional[FleetConfig] = None,
        lookahead: int = 1,
        scheduler: Optional[SchedulerConfig] = None,
        supervisors: Optional[Sequence[Any]] = None,
        telemetry: Optional[Any] = None,
    ) -> None:
        engines = list(engines)
        if not engines:
            raise ValueError("EngineFleet needs at least one engine")
        if isinstance(scheduler, SLOScheduler):
            raise TypeError(
                "pass a SchedulerConfig: each replica owns its own SLOScheduler "
                "(a shared queue instance would defeat per-replica routing)"
            )
        self.config = config or FleetConfig()
        if supervisors is None:
            supervisors = [EngineSupervisor() for _ in engines]
        supervisors = list(supervisors)
        if len(supervisors) != len(engines):
            raise ValueError(
                f"{len(engines)} engines need {len(engines)} supervisors, "
                f"got {len(supervisors)}"
            )
        block_sizes = {int(getattr(e, "_prefix_block_size", 16)) for e in engines}
        if len(block_sizes) != 1:
            raise ValueError(
                f"replicas must share one prefix block size, got {sorted(block_sizes)}"
            )
        self.router = Router(
            len(engines), block_size=block_sizes.pop(), config=self.config
        )
        #: ONE Telemetry shared fleet-wide: a trace follows its request across
        #: replicas (failover adoption keeps the same request_id), so the
        #: instruments must not be per-replica (``is not None`` guarded)
        self._telemetry = telemetry
        self._replicas: List[_Replica] = []
        for index, (engine, sup) in enumerate(zip(engines, supervisors)):
            batcher = ContinuousBatcher(
                engine,
                lookahead=lookahead,
                scheduler=SLOScheduler(scheduler),
                supervisor=sup,
                telemetry=telemetry,
            )
            # failover hand-off: the dying replica's worker thread calls this
            # with its orphaned tickets; we re-route them to survivors
            batcher.on_tickets_orphaned = (
                lambda tickets, _i=index: self._reroute_orphans(_i, tickets)
            )
            sup.subscribe(lambda old, new, _i=index: self._on_replica_state(_i, old, new))
            self._replicas.append(_Replica(index, engine, batcher, sup))
        self._lock = threading.Lock()  # lock-leaf -- guards the fleet counters ONLY
        self._closed = False  # guarded-by: _lock
        self.requests_routed = 0  # guarded-by: _lock
        self.shed_queue_full = 0  # guarded-by: _lock
        self.shed_unavailable = 0  # guarded-by: _lock
        self.rerouted_tickets = 0  # guarded-by: _lock
        self.reroute_failed = 0  # guarded-by: _lock

    # ------------------------------------------------------------- structure

    def attach_telemetry(self, telemetry: Any) -> None:
        """Wire ONE span/metrics collector into a prebuilt fleet (no-op when
        one is already attached): shared fleet-wide so traces survive
        cross-replica failover. Call before the first routed request."""
        if telemetry is None or self._telemetry is not None:
            return
        self._telemetry = telemetry
        for rep in self._replicas:
            rep.batcher.attach_telemetry(telemetry)

    @property
    def num_replicas(self) -> int:
        return len(self._replicas)

    @property
    def replicas(self) -> List[_Replica]:
        return list(self._replicas)

    @property
    def engine(self) -> Any:
        """Replica 0's engine — the HTTP layer's request-validation surface
        (``max_len``/``bucket_for``); replicas are homogeneous by contract."""
        return self._replicas[0].engine

    @property
    def supervisor(self) -> Any:
        """Replica 0's supervisor (single-replica compatibility shims only;
        fleet-aware callers read :meth:`healthz`)."""
        return self._replicas[0].supervisor

    # --------------------------------------------------------------- routing

    def _candidates(self) -> List[Tuple[int, float, float]]:
        """Snapshot ``(index, weight, load)`` for every routable replica.

        Reads supervisor and scheduler state (their own locks) BEFORE any
        router/fleet lock is taken — the lock-discipline keystone."""
        out: List[Tuple[int, float, float]] = []
        for rep in self._replicas:
            state = rep.supervisor.state
            if state not in ("ok", "degraded"):
                continue  # zero weight: never a candidate
            weight = 1.0 if state == "ok" else self.config.degraded_weight
            signal = rep.batcher.scheduler.load_signal()
            slots = max(1, int(getattr(rep.engine, "num_slots", 1)))
            ema_ms = signal.get("queue_wait_ema_ms") or 0.0
            load = (signal["depth"] + rep.engine.num_active) / slots + ema_ms / 1e3
            pool = signal.get("pool")
            if pool:
                # paged engines: a replica whose block pool is nearly
                # unreclaimable is as unattractive as a full slot table,
                # whatever its queue says (admission will head-of-line block)
                load += float(pool.get("pressure", 0.0))
            out.append((rep.index, weight, load))
        return out

    def _tel_shed(self, request_id: Optional[str], reason: str) -> None:
        """Close a request's trace on a router-level shed (before any replica
        queue was touched); no-op without telemetry or an opened trace."""
        if self._telemetry is None or request_id is None:
            return
        self._telemetry.sheds_total.inc(1.0, reason)
        self._telemetry.end_trace(request_id, "shed", reason=reason)

    def _route(
        self,
        prompt_ids: Sequence[int],
        session_id: Optional[str],
        request_id: Optional[str] = None,
    ) -> _Replica:
        with self._lock:
            if self._closed:
                self._tel_shed(request_id, "batcher_closed")
                raise EngineFailure("fleet is closed", reason="batcher_closed")
        candidates = self._candidates()
        if not candidates:
            with self._lock:
                self.shed_unavailable += 1
            self._tel_shed(request_id, "fleet_unavailable")
            raise EngineFailure(
                "no healthy replica in the fleet",
                reason="fleet_unavailable",
                retryable=True,
            )
        # fleet-level shed BEFORE any replica queue is touched: the 429
        # contract holds at the router, not just per-replica
        total_queued = sum(r.batcher.scheduler.depth for r in self._replicas)
        if total_queued >= self.config.max_queue:
            with self._lock:
                self.shed_queue_full += 1
            self._tel_shed(request_id, "queue_full")
            raise QueueFullError(
                f"fleet queue full ({total_queued} requests waiting across "
                f"{len(self._replicas)} replicas)",
                retry_after_s=self.config.retry_after_s,
            )
        index, decision = self.router.route(prompt_ids, candidates, session_id=session_id)
        with self._lock:
            self.requests_routed += 1
        if self._telemetry is not None:
            # router._lock was released by route(); telemetry is a leaf here
            self._telemetry.route_decisions_total.inc(1.0, str(decision["decision"]))
            if request_id is not None:
                self._telemetry.span(
                    request_id, "route",
                    replica=index,
                    decision=decision["decision"],
                    matched_blocks=decision["matched_blocks"],
                    digest_blocks=decision["digest_blocks"],
                    candidates=len(candidates),
                )
        return self._replicas[index]

    async def generate(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        *,
        session_id: Optional[str] = None,
        priority: Any = None,
        deadline_ms: Optional[float] = None,
        request_id: Optional[str] = None,
        **sampling,
    ) -> List[int]:
        """Route, then delegate to the chosen replica's batcher (same
        contract as ``ContinuousBatcher.generate`` + ``session_id``)."""
        if self._telemetry is not None:
            # open the trace BEFORE routing so the route/shed spans land on it;
            # the replica batcher joins it (new_trace is idempotent on an
            # active request_id)
            request_id = self._telemetry.new_trace(request_id, session_id=session_id)
            replica = self._route(prompt_ids, session_id, request_id)
        else:
            # two-arg call kept for telemetry-less fleets (wrappable in tests)
            replica = self._route(prompt_ids, session_id)
        return await replica.batcher.generate(
            prompt_ids, max_new_tokens, priority=priority, deadline_ms=deadline_ms,
            request_id=request_id, **sampling,
        )

    async def stream(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        *,
        session_id: Optional[str] = None,
        priority: Any = None,
        deadline_ms: Optional[float] = None,
        request_id: Optional[str] = None,
        **sampling,
    ):
        """Route, then stream from the chosen replica (router sheds raise on
        the first ``__anext__``, before any token, like the single-engine
        path)."""
        if self._telemetry is not None:
            request_id = self._telemetry.new_trace(request_id, session_id=session_id)
            replica = self._route(prompt_ids, session_id, request_id)
        else:
            replica = self._route(prompt_ids, session_id)
        async for token in replica.batcher.stream(
            prompt_ids, max_new_tokens, priority=priority, deadline_ms=deadline_ms,
            request_id=request_id, **sampling,
        ):
            yield token

    # -------------------------------------------------------------- failover

    def _on_replica_state(self, index: int, old: str, new: str) -> None:
        # supervisor subscriber: runs OUTSIDE the supervisor lock (see
        # EngineSupervisor.subscribe), so taking the router lock here is safe
        if new == "rebuilding":
            self.router.on_replica_rebuilding(index)
        elif new == "failed":
            self.router.on_replica_failed(index)

    def _reroute_orphans(self, dead_index: int, tickets: List[Any]) -> List[Any]:
        """Place a dead replica's orphaned tickets on survivors.

        Runs on the DEAD replica's worker thread via
        ``ContinuousBatcher.on_tickets_orphaned``. Each ticket already
        carries its transcript as prompt and its unspent budget; its salvage
        pin was released with the dead engine. Routing reuses the affinity
        scorer (the transcript digests then index on the adoptive replica,
        so the session's NEXT turn follows them there). Returns the tickets
        no survivor could adopt — the owner fails those with the structured
        unavailable error.
        """
        unplaced: List[Any] = []
        for ticket in tickets:
            placed = False
            tried = {dead_index}
            rid = getattr(ticket, "request_id", None)
            while not placed:
                candidates = [c for c in self._candidates() if c[0] not in tried]
                if not candidates:
                    break
                index, _ = self.router.route(ticket.prompt, candidates)
                tried.add(index)
                try:
                    self._replicas[index].batcher.adopt_ticket(ticket)
                    placed = True
                except Exception as exc:  # closed/racing replica: try the next
                    logger.warning(
                        "fleet failover: replica %d refused ticket (%s)%s; trying next",
                        index, exc,
                        f" (request_id={rid})" if rid is not None else "",
                    )
            if placed and self._telemetry is not None:
                self._telemetry.failover_adoptions_total.inc()
                if rid is not None:
                    # the trace stays OPEN: the same request_id now decodes on
                    # the adoptive replica — continuity IS the failover pin
                    self._telemetry.span(
                        ticket.request_id, "failover_adopt",
                        from_replica=dead_index, to_replica=index,
                        transcript_tokens=len(ticket.prompt),
                    )
            with self._lock:
                if placed:
                    self.rerouted_tickets += 1
                else:
                    self.reroute_failed += 1
                    unplaced.append(ticket)
        if tickets:
            logger.warning(
                "fleet failover: replica %d died; re-routed %d/%d orphaned tickets",
                dead_index, len(tickets) - len(unplaced), len(tickets),
            )
        return unplaced

    # ------------------------------------------------------------- lifecycle

    def drain(self, timeout_s: float = 5.0) -> None:
        """Graceful shutdown: stop routing (new requests fail fast with the
        structured closed error), then drain every replica within ONE shared
        window — same blocking contract as ``ContinuousBatcher.drain``, so
        the app's cleanup hook treats a fleet and a single batcher alike."""
        with self._lock:
            self._closed = True
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        for rep in self._replicas:
            rep.batcher.drain(max(0.0, deadline - time.monotonic()))

    def close(self) -> None:
        """Shut every replica down (queued requests fail structured)."""
        with self._lock:
            self._closed = True
        for rep in self._replicas:
            rep.batcher.close()

    # ------------------------------------------------------------------ stats

    def healthz(self) -> Dict[str, Any]:
        """The fleet ``/healthz`` body: per-replica supervisor state, overall
        ``ok``/``degraded``/``failed`` (a fleet serves while ANY replica
        does; ``degraded`` says capacity is reduced)."""
        per = []
        serving = 0
        for rep in self._replicas:
            sup_stats = rep.supervisor.stats()
            if sup_stats["health"] in ("ok", "degraded"):
                serving += 1
            per.append(
                {
                    "replica": rep.index,
                    "state": sup_stats["health"],
                    "last_fault": rep.supervisor.last_fault,
                    "rebuilds": sup_stats["rebuilds"],
                    "watchdog_trips": sup_stats["watchdog_trips"],
                }
            )
        if serving == len(per):
            state = "ok"
        elif serving > 0:
            state = "degraded"
        else:
            state = "failed"
        return {
            "state": state,
            "supervised": True,
            "fleet": True,
            "replicas": per,
            "serving_replicas": serving,
        }

    def stats(self) -> Dict[str, Any]:
        """The ``/stats`` → ``generation`` block for a fleet: aggregate
        engine counters plus the ``fleet`` sub-block (router, per-replica
        scheduler/health/prefix-cache state, failover accounting)."""
        with self._lock:
            fleet_counters = {
                "requests_routed": self.requests_routed,
                "shed_queue_full": self.shed_queue_full,
                "shed_unavailable": self.shed_unavailable,
                "rerouted_tickets": self.rerouted_tickets,
                "reroute_failed": self.reroute_failed,
            }
        per_replica = []
        for rep in self._replicas:
            eng = rep.engine
            entry: Dict[str, Any] = {
                "replica": rep.index,
                "state": rep.supervisor.state,
                "active": eng.num_active,
                "num_slots": int(getattr(eng, "num_slots", 0)),
                "scheduler": rep.batcher.scheduler.stats(),
                "supervisor": rep.supervisor.stats(),
            }
            cache = getattr(eng, "prefix_cache", None)
            if cache is not None:
                entry["prefix_cache"] = cache.stats()
            per_replica.append(entry)
        return {
            "num_slots": sum(e["num_slots"] for e in per_replica),
            "active": sum(e["active"] for e in per_replica),
            "max_len": int(getattr(self.engine, "max_len", 0)),
            "fleet": {
                "replicas": len(self._replicas),
                **fleet_counters,
                "router": self.router.stats(),
                "per_replica": per_replica,
            },
        }
