"""Serving: resident-XLA prediction apps (native aiohttp; optional FastAPI adapter)."""

from typing import Any, Optional

from unionml_tpu.serving.app import build_aiohttp_app, jsonable, load_model_artifact, run_app
from unionml_tpu.serving.continuous import ContinuousBatcher, DecodeEngine
from unionml_tpu.serving.faults import EngineFailure, FaultError, FaultPlan
from unionml_tpu.serving.fleet import EngineFleet, FleetConfig, Router, split_mesh
from unionml_tpu.serving.metrics import MetricsRegistry
from unionml_tpu.serving.prefix_cache import PrefixCache
from unionml_tpu.serving.scheduler import SchedulerConfig, SLOScheduler
from unionml_tpu.serving.slo import SLOConfig, SLOObjective, SLOTracker
from unionml_tpu.serving.speculative import SpeculativeBatcher, SpeculativeEngine
from unionml_tpu.serving.supervisor import EngineSupervisor
from unionml_tpu.serving.telemetry import Telemetry
from unionml_tpu.serving.resident import ResidentPredictor


def serving_app(
    model: Any,
    app: Any = None,
    remote: bool = False,
    app_version: Optional[str] = None,
    model_version: str = "latest",
    resident: bool = True,
    **serving_kwargs: Any,
):
    """Build or extend a serving app for a model (``unionml/fastapi.py:15`` analogue).

    - ``app=None``: returns the framework's native aiohttp application. Extra kwargs
      (``buckets``, ``seq_buckets``, ``example_features``, ``coalesce``, ...) flow to
      :func:`build_aiohttp_app`.
    - ``app`` is a FastAPI instance (when fastapi is installed): endpoints are attached
      in place, reference-compatible.
    """
    if app is None:
        return build_aiohttp_app(
            model,
            remote=remote,
            app_version=app_version,
            model_version=model_version,
            resident=resident,
            **serving_kwargs,
        )
    try:
        from fastapi import FastAPI
    except ImportError:
        FastAPI = None  # type: ignore[assignment]
    if FastAPI is not None and isinstance(app, FastAPI):
        from unionml_tpu.serving.fastapi_adapter import attach_fastapi

        return attach_fastapi(
            model,
            app,
            remote=remote,
            app_version=app_version,
            model_version=model_version,
            resident=resident,
            **serving_kwargs,
        )
    raise TypeError(
        f"Unsupported app type {type(app)!r}: pass None for the native app or a fastapi.FastAPI instance."
    )


__all__ = [
    "ContinuousBatcher",
    "DecodeEngine",
    "EngineFailure",
    "EngineFleet",
    "EngineSupervisor",
    "FaultError",
    "FaultPlan",
    "FleetConfig",
    "MetricsRegistry",
    "PrefixCache",
    "ResidentPredictor",
    "Router",
    "SLOConfig",
    "SLOObjective",
    "SLOScheduler",
    "SLOTracker",
    "SchedulerConfig",
    "SpeculativeBatcher",
    "SpeculativeEngine",
    "Telemetry",
    "split_mesh",
    "build_aiohttp_app",
    "jsonable",
    "load_model_artifact",
    "run_app",
    "serving_app",
]
