"""Host-side radix tree over token-id blocks for KV prefix caching.

Prompt-heavy serving traffic is dominated by shared prefixes — system prompts,
few-shot templates, chat history — and recomputing their prefill per request
burns the FLOPs that bound throughput. The serving engine keeps computed KV for
prompt prefixes in a device-side block pool; THIS module is the host-side index
over that pool: a radix tree whose edges are fixed-size blocks of token ids,
mapping a prompt's longest cached prefix to the pool block ids holding its KV.

Design (the vLLM/SGLang radix-cache discipline, block-granular):

- **Block granularity.** A node caches exactly ``block_size`` tokens' KV in one
  pool block; matching walks whole blocks, so a prompt sharing 10 tokens of a
  cached prefix at ``block_size=4`` restores 8 (a partial-block hit) and
  prefills the rest.
- **Refcounts.** Every matched/inserted path is acquired until the using slot
  retires; referenced nodes are never evicted, so a block can always be trusted
  while a restore or a multi-turn follow-up depends on it.
- **LRU eviction.** Allocation prefers the free list, then evicts the
  least-recently-used *leaf* with zero references (leaves only: an interior
  evict would orphan descendants whose match path runs through it).

The tree is pure host Python (no jax import): the engine owns the device pool
and performs the gather/scatter copies; this index only decides WHICH blocks
hold WHAT tokens and WHEN a block may be reused.

Paged serving (PR 13) widened this class from *index* to *allocator*: live
decode slots now draw their working blocks from the same pool through
:meth:`alloc_blocks`/:meth:`free_blocks`, and a retiring slot's full blocks are
indexed copy-free by :meth:`adopt` — the tree node takes ownership of the
slot's block instead of allocating a fresh one and device-copying KV into it.
Every pool block is therefore owned by exactly one of: the free list, a tree
node, or a live slot (``slot_blocks`` counts the last), which is what makes
"zero leaked or double-freed blocks" a teardown counter check.
"""

from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["PrefixCache", "block_key", "prefix_digests"]

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_FNV_MASK = 0xFFFFFFFFFFFFFFFF


def block_key(tokens: Sequence[int], block_index: int, block_size: int) -> Tuple[int, ...]:
    """The radix key of block ``block_index`` of ``tokens``: the tuple of that
    block's token ids. This is THE prefix-cache hashing — the tree's node keys
    (:meth:`PrefixCache._key_at`) and the fleet router's affinity digests
    (:func:`prefix_digests`) both derive from it, so the two can never disagree
    about which prompts share a cached block."""
    start = block_index * block_size
    return tuple(int(t) for t in tokens[start : start + block_size])


def prefix_digests(
    tokens: Sequence[int], block_size: int, max_blocks: Optional[int] = None
) -> List[int]:
    """Chained 64-bit FNV-1a digests of ``tokens``' block-aligned prefixes.

    ``digests[i]`` summarizes blocks ``0..i`` (each via :func:`block_key`), and
    each digest folds in its predecessor, so equal digests mean equal whole
    *prefixes* — exactly the property a router needs to guess which replica's
    radix tree holds a prompt's longest cached chain without shipping token
    ids around. Deterministic across processes (unlike ``hash()``), cheap
    (pure host integer math), and block-granular like the tree itself: a
    prompt shorter than one block has no digest and no affinity.
    """
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    total = len(tokens) // block_size
    if max_blocks is not None:
        total = min(total, max_blocks)
    digests: List[int] = []
    acc = _FNV_OFFSET
    for index in range(total):
        for tok in block_key(tokens, index, block_size):
            # mix each token id byte-wise so nearby ids diverge fully
            val = int(tok) & _FNV_MASK
            for _ in range(8):
                acc = ((acc ^ (val & 0xFF)) * _FNV_PRIME) & _FNV_MASK
                val >>= 8
        digests.append(acc)
    return digests


class _Node:
    """One cached block: ``key`` (the block's token ids) under ``parent``."""

    __slots__ = ("key", "block_id", "parent", "children", "refcount", "last_used")

    def __init__(self, key: Tuple[int, ...], block_id: int, parent: Optional["_Node"]) -> None:
        self.key = key
        self.block_id = block_id
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.refcount = 0
        self.last_used = 0


class PrefixCache:
    """Block-granular radix index mapping token-id prefixes to pool block ids.

    :param num_blocks: capacity of the device block pool this index manages.
    :param block_size: tokens cached per block (match/insert granularity).

    Protocol (driven by :class:`~unionml_tpu.serving.continuous.DecodeEngine`):
    :meth:`match` walks the longest cached chain of full blocks for a prompt and
    acquires a reference on every matched node; after the uncovered suffix
    prefills, :meth:`extend` indexes the prompt's remaining full blocks
    (allocating pool blocks, evicting LRU unreferenced leaves as needed) and the
    caller device-copies KV into the NEW blocks it returns. :meth:`release`
    drops the path's references when the slot retires. Counters
    (:meth:`stats`) make the hit rate and eviction churn observable.
    """

    def __init__(self, num_blocks: int, block_size: int, *, telemetry=None) -> None:
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        #: optional Telemetry mirror for hit-rate counters (``is not None`` guarded)
        self.telemetry = telemetry
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._root = _Node((), -1, None)
        # pop() takes from the tail: keep ids ascending for readable tests/logs
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self._tick = 0
        self.lookups = 0
        self.hits = 0
        self.hit_tokens = 0
        self.inserted_blocks = 0
        self.evicted_blocks = 0
        self.pinned_blocks = 0
        #: blocks currently owned by live decode slots (paged serving); the
        #: engine acquires them via alloc_blocks and returns them via
        #: free_blocks or adopt — teardown asserts this is back to zero
        self.slot_blocks = 0
        self.adopted_blocks = 0

    @property
    def cached_blocks(self) -> int:
        """Pool blocks currently holding indexed KV (tree-owned: excludes both
        the free list and live slots' working blocks)."""
        return self.num_blocks - len(self._free) - self.slot_blocks

    def _key_at(self, tokens: Sequence[int], block_index: int) -> Tuple[int, ...]:
        return block_key(tokens, block_index, self.block_size)

    def match(self, tokens: Sequence[int], max_blocks: int) -> List[_Node]:
        """Longest cached chain of full blocks covering ``tokens``, up to
        ``max_blocks``. Bumps recency and ACQUIRES a reference on every matched
        node — callers must :meth:`release` the returned path when done."""
        self._tick += 1
        self.lookups += 1
        if self.telemetry is not None:
            self.telemetry.prefix_lookups_total.inc()
        node, path = self._root, []  # type: ignore[var-annotated]
        while len(path) < max_blocks:
            child = node.children.get(self._key_at(tokens, len(path)))
            if child is None:
                break
            child.last_used = self._tick
            child.refcount += 1
            path.append(child)
            node = child
        return path

    def probe(self, tokens: Sequence[int], max_blocks: int) -> int:
        """Length (in blocks) :meth:`match` would return — WITHOUT acquiring
        references or touching recency/counters. Used by admission scheduling
        to compare a live match against what a same-batch sibling will insert."""
        node, depth = self._root, 0
        while depth < max_blocks:
            child = node.children.get(self._key_at(tokens, depth))
            if child is None:
                break
            depth += 1
            node = child
        return depth

    def record_hit(self, matched_tokens: int) -> None:
        """Count one served hit of ``matched_tokens`` restored-prefix tokens
        (called by the engine with the FINAL matched length, after any
        capacity-driven shrink, so counters reflect KV actually reused)."""
        if matched_tokens > 0:
            self.hits += 1
            self.hit_tokens += int(matched_tokens)
            if self.telemetry is not None:
                self.telemetry.prefix_hits_total.inc()
                self.telemetry.prefix_hit_tokens_total.inc(float(matched_tokens))

    def extend(
        self, path: List[_Node], tokens: Sequence[int], max_blocks: int
    ) -> Tuple[List[_Node], List[_Node]]:
        """Index ``tokens``' full blocks beyond ``path``, up to ``max_blocks``.

        Existing nodes (a sibling indexed them first) are acquired in place; a
        missing node allocates a pool block — evicting the LRU unreferenced
        leaf when the free list is empty — and is returned in ``new`` for the
        caller to device-copy KV into. Stops early (keeping the indexed chain a
        true prefix) when every pool block is referenced. Returns
        ``(full_path, new_nodes)``; ``new_nodes`` is always the tail of
        ``full_path``, and every node of ``full_path`` holds a reference the
        caller must eventually :meth:`release`.
        """
        self._tick += 1
        node = path[-1] if path else self._root
        full, new = list(path), []  # type: ignore[var-annotated]
        while len(full) < max_blocks:
            key = self._key_at(tokens, len(full))
            child = node.children.get(key)
            if child is None:
                block_id = self._alloc()
                if block_id is None:  # every block referenced: cannot evict
                    break
                child = _Node(key, block_id, node)
                node.children[key] = child
                new.append(child)
                self.inserted_blocks += 1
            child.last_used = self._tick
            child.refcount += 1
            full.append(child)
            node = child
        return full, new

    def alloc_blocks(self, n: int) -> Optional[List[int]]:
        """Acquire ``n`` pool blocks for a live slot's working set (paged
        admission), evicting LRU unreferenced leaves as needed. All-or-nothing:
        returns ``None`` — with nothing allocated — if fewer than ``n`` blocks
        can be freed, so a failed admission never strands a partial grant.
        The caller owns the returned ids until :meth:`free_blocks` or
        :meth:`adopt` hands each one back."""
        ids: List[int] = []
        for _ in range(n):
            block_id = self._alloc()
            if block_id is None:
                self._free.extend(reversed(ids))  # rollback, preserving order
                return None
            ids.append(block_id)
        self.slot_blocks += n
        return ids

    def free_blocks(self, ids: Sequence[int]) -> None:
        """Return slot-owned blocks (from :meth:`alloc_blocks`) to the free
        list — the paged engine calls this when a slot retires with blocks the
        radix index did not :meth:`adopt` (partial tail, unused budget)."""
        self._free.extend(int(b) for b in ids)
        self.slot_blocks -= len(ids)
        assert self.slot_blocks >= 0, "freed more slot blocks than were allocated"

    def available_blocks(self) -> int:
        """Blocks an :meth:`alloc_blocks` call could acquire right now: the
        free list plus every evictable (transitively unreferenced) tree chain.
        Admission gates block demand on this without mutating the tree."""
        def reclaim(node: _Node) -> Tuple[int, bool]:
            # (reclaimable blocks in the subtree, whole subtree evictable?):
            # leaves-only eviction frees a node iff all its descendants go
            # first, but a referenced parent doesn't shield evictable leaf
            # chains below it. Depth is bounded by max_len/block_size.
            count, fully = 0, True
            for child in node.children.values():
                sub, sub_fully = reclaim(child)
                count += sub
                fully = fully and sub_fully
            if fully and node.refcount <= 0:
                return count + 1, True
            return count, False

        total = 0
        for child in self._root.children.values():
            total += reclaim(child)[0]
        return len(self._free) + total

    def adopt(
        self,
        path: List[_Node],
        tokens: Sequence[int],
        max_blocks: int,
        block_map: Dict[int, int],
    ) -> Tuple[List[_Node], int]:
        """Copy-free :meth:`extend`: index ``tokens``' full blocks beyond
        ``path`` by transferring ownership of the caller's own pool blocks.

        ``block_map`` maps block index -> the slot-owned block id already
        holding that block's KV (the slot's table wrote it there during
        decode). A missing tree node ADOPTS the mapped block — the id is popped
        from ``block_map`` and ownership moves slot -> tree, no device copy.
        Where a sibling indexed the same block first, the existing node is
        acquired and the slot keeps (and later frees) its duplicate. Returns
        ``(full_path, adopted)``; every node of ``full_path`` holds a reference
        the caller must eventually :meth:`release`.
        """
        self._tick += 1
        node = path[-1] if path else self._root
        full = list(path)
        adopted = 0
        while len(full) < max_blocks:
            key = self._key_at(tokens, len(full))
            child = node.children.get(key)
            if child is None:
                block_id = block_map.pop(len(full), None)
                if block_id is None:  # caller has no block for this index
                    break
                child = _Node(key, block_id, node)
                node.children[key] = child
                adopted += 1
                self.inserted_blocks += 1
                self.adopted_blocks += 1
                self.slot_blocks -= 1  # ownership: slot -> tree
            child.last_used = self._tick
            child.refcount += 1
            full.append(child)
            node = child
        return full, adopted

    def release(self, path: Sequence[_Node]) -> None:
        """Drop one reference from every node of ``path`` (slot retirement)."""
        for node in path:
            node.refcount -= 1

    def pin(self, path: Sequence[_Node]) -> None:
        """Acquire an eviction-proof reference on every node of ``path``.

        A PREEMPTED request's checkpoint lives only in these blocks: evicting
        one before the resume re-admits would silently turn the resume into a
        full re-prefill (or corrupt a partially-matched chain), so the pin
        holds a reference across the whole queued gap — the engine's slot
        references come and go with slots, this one belongs to the scheduler's
        ticket. ``pinned_blocks`` (see :meth:`stats`) makes leak detection a
        counter read: it must return to zero once every preempted request has
        resumed or been cancelled.
        """
        for node in path:
            node.refcount += 1
        self.pinned_blocks += len(path)

    def unpin(self, path: Sequence[_Node]) -> None:
        """Drop a :meth:`pin`'s references (resume re-admitted, or the
        preempted request was cancelled while re-queued)."""
        for node in path:
            node.refcount -= 1
        # clear() may have reset the counter while paths were still pinned
        # (engine reset drops the whole tree); never let it go negative
        self.pinned_blocks = max(0, self.pinned_blocks - len(path))

    def clear(self) -> None:
        """Forget every cached block (engine reset: the pool is reallocated).
        Slot-owned blocks are reclaimed too — the paged engine only calls this
        when every slot's device state is being rebuilt with it."""
        self._root = _Node((), -1, None)
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self.pinned_blocks = 0
        self.slot_blocks = 0

    def _alloc(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        victim = self._lru_leaf()
        if victim is None:
            return None
        self._evict(victim)
        return self._free.pop()

    def _lru_leaf(self) -> Optional[_Node]:
        best: Optional[_Node] = None
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            elif node.refcount <= 0 and (best is None or node.last_used < best.last_used):
                best = node
        return best

    def _evict(self, node: _Node) -> None:
        assert node.parent is not None and not node.children
        del node.parent.children[node.key]
        self._free.append(node.block_id)
        self.evicted_blocks += 1

    def stats(self) -> Dict[str, int]:
        """Counters for /stats and the prefix-heavy bench."""
        return {
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "cached_blocks": self.cached_blocks,
            "lookups": self.lookups,
            "hits": self.hits,
            "hit_tokens": self.hit_tokens,
            "inserted_blocks": self.inserted_blocks,
            "evicted_blocks": self.evicted_blocks,
            "pinned_blocks": self.pinned_blocks,
            # paged-pool occupancy: live working blocks, free headroom, and
            # copy-free index adoptions (all zero on a dense-mode engine)
            "slot_blocks": self.slot_blocks,
            "free_blocks": len(self._free),
            "adopted_blocks": self.adopted_blocks,
        }
