"""Debug & sanitization utilities: the jit-era analogues of race detectors.

Reference state: no sanitizers exist (SURVEY.md §5 — concurrency in-framework is nil).
In a compiled framework the corresponding failure modes are impure traced functions
(side effects silently frozen at trace time), NaN-producing steps, and accidental
retracing; these helpers surface each.
"""

import contextlib
from typing import Any, Callable, Iterator

import jax

from unionml_tpu._logging import logger


@contextlib.contextmanager
def debug_nans(enabled: bool = True) -> Iterator[None]:
    """Raise at the op that first produces a NaN inside jitted code."""
    previous = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", enabled)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", previous)


@contextlib.contextmanager
def check_tracer_leaks() -> Iterator[None]:
    """Error on traced values escaping their trace (the classic impurity bug)."""
    previous = jax.config.jax_check_tracer_leaks
    jax.config.update("jax_check_tracer_leaks", True)
    try:
        yield
    finally:
        jax.config.update("jax_check_tracer_leaks", previous)


def assert_pure(fn: Callable, *example_args: Any, atol: float = 1e-5) -> None:
    """Assert ``fn`` is trace-pure: eager and compiled evaluations agree in structure
    and values (``atol=0`` demands exact equality).

    Catches functions that read mutable global state or mutate inputs — those behave
    differently between eager calls and their once-traced compiled form.
    """
    import numpy as np

    eager = fn(*example_args)
    compiled = jax.jit(fn)(*example_args)
    eager_tree = jax.tree_util.tree_structure(eager)
    compiled_tree = jax.tree_util.tree_structure(compiled)
    assert eager_tree == compiled_tree, (
        f"output structure differs between eager ({eager_tree}) and traced ({compiled_tree}) evaluation"
    )
    for e_leaf, c_leaf in zip(jax.tree_util.tree_leaves(eager), jax.tree_util.tree_leaves(compiled)):
        np.testing.assert_allclose(np.asarray(e_leaf), np.asarray(c_leaf), atol=atol)


class RetraceMonitor:
    """Counts how often a jitted function re-traces (shape/dtype churn detector).

    Excess retracing is the compiled-framework performance bug: every new input
    signature pays full compilation. Wrap the function, run the workload, then check
    ``monitor.traces`` — more than a handful means the input pipeline leaks shapes.
    """

    def __init__(self, fn: Callable, name: str = None):
        self.traces = 0
        self.name = name or getattr(fn, "__name__", "fn")

        def counted(*args, **kwargs):
            # graftlint: disable=retrace -- the trace-time side effect IS the feature: this counter exists to count retraces
            self.traces += 1
            if self.traces > 1:
                logger.warning("%s re-traced (trace #%d) — check for shape/dtype churn", self.name, self.traces)
            return fn(*args, **kwargs)

        self.wrapped = jax.jit(counted)

    def __call__(self, *args, **kwargs):
        return self.wrapped(*args, **kwargs)
