"""{{app_name}}: digits classifier packaged and served through BentoML.

Reference parity: the upstream `basic-bentoml` scaffold. Train locally, save the
model object into the bento model store, `bentoml build` the service, and serve
the built bento — the runnable advertises TPU resources and holds a resident
compiled predictor.
"""

from typing import List

import pandas as pd
from sklearn.datasets import load_digits
from sklearn.linear_model import LogisticRegression

from unionml_tpu import Dataset, Model

dataset = Dataset(name="{{app_name}}_dataset", test_size=0.2, shuffle=True, targets=["target"])
model = Model(name="{{app_name}}", init=LogisticRegression, dataset=dataset)


@dataset.reader
def reader() -> pd.DataFrame:
    return load_digits(as_frame=True).frame


@model.trainer
def trainer(estimator: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame) -> LogisticRegression:
    return estimator.fit(features, target.squeeze())


@model.predictor
def predictor(estimator: LogisticRegression, features: pd.DataFrame) -> List[float]:
    return [float(x) for x in estimator.predict(features)]


@model.evaluator
def evaluator(estimator: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame) -> float:
    from sklearn.metrics import accuracy_score

    return float(accuracy_score(target.squeeze(), estimator.predict(features)))


if __name__ == "__main__":
    from unionml_tpu.services.bentoml_service import BentoMLService

    model.train(hyperparameters={"C": 1.0, "max_iter": 5000})
    # bentoml tags must be lowercase; the app name is any valid Python identifier
    saved = BentoMLService(model).save_model(name="{{app_name}}".lower())
    print(f"saved to the bento model store: {saved.tag}")
    print("next: bentoml build && bentoml serve " + "{{app_name}}".lower() + ":latest")
