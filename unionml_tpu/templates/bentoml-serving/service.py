"""BentoML service definition for {{app_name}} (`bentofile.yaml` points here)."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))

from app import model

from unionml_tpu.services.bentoml_service import BentoMLService

service = BentoMLService(model)
# bentoml tags must be lowercase; the app name is any valid Python identifier
BENTO_NAME = "{{app_name}}".lower()
svc = service.configure(f"{BENTO_NAME}:latest", name=BENTO_NAME)
