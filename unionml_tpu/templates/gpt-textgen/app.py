"""{{app_name}}: character-level GPT text generation through the Dataset/Model API.

The decoder-family story end to end: the reader yields tokenized sequences, the
trainer runs a jit-compiled next-token loop, the predictor GENERATES continuations
with the KV-cache decode path — so `unionml-tpu serve` answers prompts over HTTP.
"""

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from unionml_tpu import Dataset, Model
from unionml_tpu.models import GPTConfig, GPTLMHeadModel, TrainState, create_train_state
from unionml_tpu.models.gpt import generate, init_params, lm_loss

SEQ_LEN = 64
VOCAB = 128  # ASCII char-level

dataset = Dataset(name="{{app_name}}_dataset", test_size=0.1)

config = GPTConfig.tiny(vocab_size=VOCAB, max_position_embeddings=2 * SEQ_LEN, dropout=0.0)
gpt = GPTLMHeadModel(config)


def encode(text: str) -> np.ndarray:
    return np.frombuffer(text.encode("ascii", "replace"), dtype=np.uint8).astype(np.int32) % VOCAB


def decode(ids) -> str:
    return bytes(int(i) for i in ids).decode("ascii", "replace")


def init(learning_rate: float = 3e-3) -> TrainState:
    variables = init_params(config, seq_len=SEQ_LEN)
    return create_train_state(gpt, variables, learning_rate=learning_rate, max_grad_norm=1.0)


model = Model(name="{{app_name}}", init=init, dataset=dataset)


@dataset.reader
def reader(n: int = 256, seed: int = 0) -> Dict[str, np.ndarray]:
    """Synthetic corpus: repeated pangram text; swap in your own text file."""
    corpus = encode("the quick brown fox jumps over the lazy dog. " * 200)
    rng = np.random.default_rng(seed)
    starts = rng.integers(0, len(corpus) - SEQ_LEN - 1, size=n)
    ids = np.stack([corpus[s : s + SEQ_LEN] for s in starts])
    return {"input_ids": ids}


@model.trainer
def trainer(
    state: TrainState,
    features: Dict[str, np.ndarray],
    targets: Dict[str, np.ndarray],
    *,
    num_steps: int = 200,
    batch_size: int = 32,
) -> TrainState:
    ids_all = np.asarray(features["input_ids"])
    rng = np.random.default_rng(0)

    @jax.jit
    def step(state, batch):
        def loss_fn(params):
            logits = state.apply_fn({"params": params}, batch, deterministic=True)
            return lm_loss(logits, batch)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    for i in range(num_steps):
        idx = rng.integers(0, len(ids_all), size=batch_size)
        state, loss = step(state, jnp.asarray(ids_all[idx]))
        if (i + 1) % 50 == 0:
            print(f"step {i + 1}: lm loss {float(loss):.3f}")
    return state


@model.predictor
def predictor(state: TrainState, features: Dict[str, np.ndarray]) -> np.ndarray:
    """Generate continuations: features carry 'prompt' strings or 'prompt_ids' arrays."""
    if "prompt" in features:
        prompts = [encode(p) for p in features["prompt"]]
    elif "prompt_ids" in features:
        prompts = [np.asarray(p) for p in features["prompt_ids"]]
    else:
        raise ValueError("features must contain 'prompt' (strings) or 'prompt_ids' (token arrays)")
    if not prompts or any(len(p) == 0 for p in prompts):
        raise ValueError("every prompt must contain at least one token")

    max_new = min(int(features.get("max_new_tokens", 32)), config.max_position_embeddings - 1)
    # keep the rightmost context that still leaves room for the new tokens
    keep = config.max_position_embeddings - max_new
    prompts = [p[-keep:] for p in prompts]

    # ragged prompts batch through ONE generate call: rows left-pad to the longest
    # prompt and prompt_mask keeps attention/positions exact per row. Uniform-length
    # batches skip the mask so prefill keeps the maskless flash-attention fast path.
    width = max(len(p) for p in prompts)
    ragged = any(len(p) != width for p in prompts)
    batch_ids = np.zeros((len(prompts), width), dtype=np.int32)
    mask = np.zeros((len(prompts), width), dtype=np.int32)
    for row, p in enumerate(prompts):
        batch_ids[row, width - len(p) :] = p
        mask[row, width - len(p) :] = 1
    out = generate(
        gpt,
        {"params": state.params},
        jnp.asarray(batch_ids),
        max_new_tokens=max_new,
        max_len=width + max_new,
        prompt_mask=jnp.asarray(mask) if ragged else None,
    )
    return np.asarray(out)


@model.evaluator
def evaluator(state: TrainState, features: Dict[str, np.ndarray], targets: Dict[str, np.ndarray]) -> float:
    ids = jnp.asarray(features["input_ids"])
    logits = state.apply_fn({"params": state.params}, ids, deterministic=True)
    return float(lm_loss(logits, ids))


if __name__ == "__main__":
    state, metrics = model.train(trainer_kwargs={"num_steps": 300})
    print(f"metrics (lm loss per split): {metrics}")
    model.save("gpt_model.ckpt")
    out = model.predict(features={"prompt": ["the quick brown "], "max_new_tokens": 24})
    print("generated:", repr(decode(out[0])))
