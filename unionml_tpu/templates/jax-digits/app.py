"""{{app_name}}: jax-native digits MLP — the trainer is a compiled fit() loop."""

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
from sklearn.datasets import load_digits

from unionml_tpu import Dataset, Model
from unionml_tpu.models import MLPClassifier, TrainState, create_train_state, fit, make_classifier_eval_step

dataset = Dataset(name="{{app_name}}_dataset", test_size=0.2, targets=["target"], device_format="jax")

mlp = MLPClassifier(hidden_sizes=(128,), num_classes=10)


def init(learning_rate: float = 1e-3) -> TrainState:
    params = mlp.init(jax.random.PRNGKey(0), jnp.zeros((1, 64)))
    return create_train_state(mlp, params, learning_rate=learning_rate)


model = Model(name="{{app_name}}", init=init, dataset=dataset)


@dataset.reader
def reader() -> pd.DataFrame:
    return load_digits(as_frame=True).frame


@model.trainer
def trainer(state: TrainState, features: jax.Array, target: jax.Array, *, num_epochs: int = 30) -> TrainState:
    data = {"inputs": np.asarray(features), "labels": np.asarray(target).reshape(-1).astype(np.int32)}
    return fit(state, data, batch_size=512, num_epochs=num_epochs, log_every=10_000).state


@model.predictor
def predictor(state: TrainState, features: jax.Array) -> jax.Array:
    return jnp.argmax(state.apply_fn({"params": state.params}, features), axis=-1).astype(jnp.float32)


@model.evaluator
def evaluator(state: TrainState, features: jax.Array, target: jax.Array) -> float:
    metrics = make_classifier_eval_step()(
        state, {"inputs": features, "labels": jnp.asarray(np.asarray(target).reshape(-1), dtype=jnp.int32)}
    )
    return float(metrics["accuracy"])


if __name__ == "__main__":
    state, metrics = model.train(hyperparameters={"learning_rate": 1e-3})
    print(f"metrics: {metrics}")
    model.save("model.ckpt")
