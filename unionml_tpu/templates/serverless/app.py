"""{{app_name}}: digits classifier served through a serverless event handler.

The handler speaks API-Gateway-style HTTP events and storage-notification events —
deployable to any FaaS runtime that invokes ``handler(event, context)``.
"""

from typing import List

import pandas as pd
from sklearn.datasets import load_digits
from sklearn.linear_model import LogisticRegression

from unionml_tpu import Dataset, Model
from unionml_tpu.services import make_event_handler

dataset = Dataset(name="{{app_name}}_dataset", test_size=0.2, shuffle=True, targets=["target"])
model = Model(name="{{app_name}}", init=LogisticRegression, dataset=dataset)


@dataset.reader
def reader() -> pd.DataFrame:
    return load_digits(as_frame=True).frame


@model.trainer
def trainer(estimator: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame) -> LogisticRegression:
    return estimator.fit(features, target.squeeze())


@model.predictor
def predictor(estimator: LogisticRegression, features: pd.DataFrame) -> List[float]:
    return [float(x) for x in estimator.predict(features)]


@model.evaluator
def evaluator(estimator: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame) -> float:
    from sklearn.metrics import accuracy_score

    return float(accuracy_score(target.squeeze(), estimator.predict(features)))


# the FaaS entrypoint: reads the model from UNIONML_MODEL_PATH at first invocation
handler = make_event_handler(model)


if __name__ == "__main__":
    import json

    model.train(hyperparameters={"C": 1.0, "max_iter": 5000})
    model.save("model.joblib")

    import os

    os.environ["UNIONML_MODEL_PATH"] = "model.joblib"
    sample = load_digits(as_frame=True).frame.sample(2, random_state=0).drop(columns=["target"])
    event = {"body": json.dumps({"features": sample.to_dict(orient="records")})}
    print(handler(event, None))
