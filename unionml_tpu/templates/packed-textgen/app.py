"""{{app_name}}: packed-sequence GPT training through the Dataset/Model API.

Real corpora are RAGGED — sentences, comments, log lines of wildly different
lengths. Fixed-shape rows waste most of the batch on padding; this scaffold
trains on packed rows instead: the reader yields ragged token sequences, the
trainer hands them to :func:`unionml_tpu.models.training.fit_lm` with
``pack=True`` (first-fit packing + segment-confined attention + per-segment
positions), and the predictor generates with the KV-cache decode path.

A capability the reference cannot express at all (its training loop is opaque
user code — reference ``unionml/model.py:560`` runs the trainer inline, with no
packing support anywhere in the framework).
"""

from typing import Dict, List

import jax.numpy as jnp
import numpy as np

from unionml_tpu import Dataset, Model
from unionml_tpu.models import GPTConfig, GPTLMHeadModel, TrainState, create_train_state
from unionml_tpu.models.gpt import generate, init_params, lm_loss
from unionml_tpu.models.training import fit_lm

SEQ_LEN = 64
VOCAB = 128  # ASCII char-level

dataset = Dataset(name="{{app_name}}_dataset", test_size=0.1)

config = GPTConfig.tiny(vocab_size=VOCAB, max_position_embeddings=2 * SEQ_LEN, dropout=0.0)
gpt = GPTLMHeadModel(config)


def encode(text: str) -> np.ndarray:
    return np.frombuffer(text.encode("ascii", "replace"), dtype=np.uint8).astype(np.int32) % VOCAB


def decode(ids) -> str:
    return bytes(int(i) for i in ids).decode("ascii", "replace")


def init(learning_rate: float = 3e-3) -> TrainState:
    variables = init_params(config, seq_len=SEQ_LEN)
    return create_train_state(gpt, variables, learning_rate=learning_rate, max_grad_norm=1.0)


model = Model(name="{{app_name}}", init=init, dataset=dataset)


@model.dataset.reader
def reader(n: int = 256, seed: int = 0) -> Dict[str, list]:
    """Ragged corpus: sentences of varying length (swap in your own text file)."""
    sentences = [
        "the quick brown fox jumps over the lazy dog.",
        "pack short sequences together.",
        "segment ids confine attention.",
        "positions restart at each segment start.",
        "no cross-segment loss transitions.",
        "a longer sentence pays for itself because the packer places it first and fills the row tail with short ones.",
    ]
    rng = np.random.default_rng(seed)
    picks = rng.integers(0, len(sentences), size=n)
    return {"sequences": [encode(sentences[i]).tolist() for i in picks]}


@model.trainer
def trainer(
    state: TrainState,
    features: Dict[str, list],
    targets: Dict[str, list],
    *,
    num_epochs: int = 20,
    batch_size: int = 16,
    pack: bool = True,
) -> TrainState:
    sequences: List[np.ndarray] = [np.asarray(s, dtype=np.int32) for s in features["sequences"]]
    result = fit_lm(
        state,
        sequences,
        seq_len=SEQ_LEN,
        batch_size=batch_size,
        pack=pack,
        num_epochs=num_epochs,
        log_every=50,
    )
    return result.state


@model.predictor
def predictor(state: TrainState, features: Dict[str, list]) -> np.ndarray:
    """Generate continuations: features carry 'prompt' strings or 'prompt_ids' arrays."""
    if "prompt" in features:
        prompts = [encode(p) for p in features["prompt"]]
    elif "prompt_ids" in features:
        prompts = [np.asarray(p) for p in features["prompt_ids"]]
    else:
        raise ValueError("features must contain 'prompt' (strings) or 'prompt_ids' (token arrays)")
    if not prompts or any(len(p) == 0 for p in prompts):
        raise ValueError("every prompt must contain at least one token")

    max_new = min(int(features.get("max_new_tokens", 32)), config.max_position_embeddings - 1)
    keep = config.max_position_embeddings - max_new
    prompts = [p[-keep:] for p in prompts]

    width = max(len(p) for p in prompts)
    ragged = any(len(p) != width for p in prompts)
    batch_ids = np.zeros((len(prompts), width), dtype=np.int32)
    mask = np.zeros((len(prompts), width), dtype=np.int32)
    for row, p in enumerate(prompts):
        batch_ids[row, width - len(p) :] = p
        mask[row, width - len(p) :] = 1
    out = generate(
        gpt,
        {"params": state.params},
        jnp.asarray(batch_ids),
        max_new_tokens=max_new,
        max_len=width + max_new,
        prompt_mask=jnp.asarray(mask) if ragged else None,
    )
    return np.asarray(out)


@model.evaluator
def evaluator(state: TrainState, features: Dict[str, list], targets: Dict[str, list]) -> float:
    """Held-out LM loss on right-padded rows (evaluation needs no packing)."""
    sequences = [np.asarray(s, dtype=np.int32)[:SEQ_LEN] for s in features["sequences"]]
    ids = np.zeros((len(sequences), SEQ_LEN), dtype=np.int32)
    mask = np.zeros((len(sequences), SEQ_LEN), dtype=np.float32)
    for i, s in enumerate(sequences):
        ids[i, : len(s)] = s
        mask[i, : len(s)] = 1.0
    logits = gpt.apply({"params": state.params}, jnp.asarray(ids), deterministic=True)
    return float(lm_loss(logits, jnp.asarray(ids), mask=jnp.asarray(mask)))


if __name__ == "__main__":
    state, metrics = model.train(trainer_kwargs={"num_epochs": 30})
    print(f"metrics (lm loss per split): {metrics}")
    model.save("packed_gpt_model.ckpt")
    out = model.predict(features={"prompt": ["the quick "], "max_new_tokens": 24})
    print("generated:", repr(decode(out[0])))
