"""{{app_name}}: data-parallel training over a TPU mesh (v5e-8 layout).

The trainer builds a mesh over all visible devices, shards each batch over the
``data`` axis, and lets XLA all-reduce gradients over ICI. Test locally with
``XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu``.
"""

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from unionml_tpu import Dataset, Model
from unionml_tpu.defaults import TPU_V5E_8
from unionml_tpu.models import MLPClassifier, TrainState, create_train_state, fit, make_classifier_eval_step
from unionml_tpu.parallel import make_mesh

dataset = Dataset(name="{{app_name}}_dataset", test_size=0.2, targets=["labels"])

mlp = MLPClassifier(hidden_sizes=(256, 128), num_classes=10)


def init(learning_rate: float = 1e-3) -> TrainState:
    params = mlp.init(jax.random.PRNGKey(0), jnp.zeros((1, 64)))
    return create_train_state(mlp, params, learning_rate=learning_rate)


model = Model(name="{{app_name}}", init=init, dataset=dataset)
# deployed jobs request a v5e-8 slice (never a GPU)
model.remote(resources=TPU_V5E_8)


@dataset.reader
def reader(n: int = 8192, seed: int = 0) -> Dict[str, np.ndarray]:
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n)
    inputs = rng.normal(size=(n, 64)).astype(np.float32) + labels[:, None] * 0.3
    return {"inputs": inputs, "labels": labels.astype(np.int32)}


@model.trainer
def trainer(
    state: TrainState,
    features: Dict[str, np.ndarray],
    targets: Dict[str, np.ndarray],
    *,
    num_epochs: int = 5,
    batch_size: int = 1024,
) -> TrainState:
    mesh = make_mesh()  # 1-D data axis over every visible device
    data = {"inputs": features["inputs"], "labels": targets["labels"]}
    result = fit(state, data, batch_size=batch_size, num_epochs=num_epochs, mesh=mesh, log_every=20)
    print(f"mesh={mesh.shape} throughput: {result.examples_per_s:.0f} examples/s")
    return result.state


@model.predictor
def predictor(state: TrainState, features: Dict[str, np.ndarray]) -> jax.Array:
    logits = state.apply_fn({"params": state.params}, jnp.asarray(features["inputs"]))
    return jnp.argmax(logits, axis=-1)


@model.evaluator
def evaluator(state: TrainState, features: Dict[str, np.ndarray], targets: Dict[str, np.ndarray]) -> float:
    metrics = make_classifier_eval_step()(
        state, {"inputs": jnp.asarray(features["inputs"]), "labels": jnp.asarray(targets["labels"])}
    )
    return float(metrics["accuracy"])


if __name__ == "__main__":
    state, metrics = model.train()
    print(f"metrics: {metrics}")
