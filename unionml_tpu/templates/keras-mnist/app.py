"""{{app_name}}: Keras MNIST CNN — the reference's Keras tutorial config, framework-served.

Opaque-keras path: the trainer runs keras's own fit loop eagerly; persistence uses the
keras default saver/loader (.keras format). Config mirrors the reference recipe
(batch 512, lr 3e-4).
"""

from typing import Dict, List

import keras
import numpy as np

from unionml_tpu import Dataset, Model

dataset = Dataset(name="{{app_name}}_dataset", test_size=0.2, targets=["labels"])


def build_cnn(learning_rate: float = 3e-4) -> keras.Model:
    net = keras.Sequential(
        [
            keras.layers.Input((28, 28, 1)),
            keras.layers.Conv2D(32, 3, activation="relu"),
            keras.layers.MaxPooling2D(2),
            keras.layers.Conv2D(64, 3, activation="relu"),
            keras.layers.MaxPooling2D(2),
            keras.layers.Flatten(),
            keras.layers.Dense(128, activation="relu"),
            keras.layers.Dense(10),
        ]
    )
    net.compile(
        optimizer=keras.optimizers.Adam(learning_rate),
        loss=keras.losses.SparseCategoricalCrossentropy(from_logits=True),
        metrics=["accuracy"],
    )
    return net


model = Model(name="{{app_name}}", init=build_cnn, dataset=dataset)


@dataset.reader
def reader(n: int = 4096, seed: int = 0) -> Dict[str, np.ndarray]:
    """Synthetic MNIST-shaped data; swap in keras.datasets.mnist.load_data() online."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n)
    images = (rng.normal(size=(n, 28, 28)) + labels[:, None, None] * 0.15).astype(np.float32)
    return {"images": images, "labels": labels.astype(np.int32)}


@model.trainer
def trainer(
    net: keras.Model,
    features: Dict[str, np.ndarray],
    targets: Dict[str, np.ndarray],
    *,
    batch_size: int = 512,
    epochs: int = 10,
) -> keras.Model:
    net.fit(
        features["images"][..., None],
        targets["labels"],
        batch_size=batch_size,
        epochs=epochs,
        verbose=0,
    )
    return net


@model.predictor
def predictor(net: keras.Model, features: Dict[str, np.ndarray]) -> List[float]:
    logits = net.predict(features["images"][..., None], verbose=0)
    return [float(x) for x in logits.argmax(axis=1)]


@model.evaluator
def evaluator(net: keras.Model, features: Dict[str, np.ndarray], targets: Dict[str, np.ndarray]) -> float:
    _, accuracy = net.evaluate(features["images"][..., None], targets["labels"], verbose=0)
    return float(accuracy)


if __name__ == "__main__":
    net, metrics = model.train(hyperparameters={"learning_rate": 3e-4}, trainer_kwargs={"epochs": 3})
    print(f"metrics: {metrics}")
    model.save("mnist_cnn.keras")
