"""{{app_name}}: pytorch MLP digits classifier — the opaque-trainer path.

The reference's pytorch quickstart shape (a user-owned torch loop inside
@model.trainer): the framework runs the trainer eagerly (torch objects can't be
jit-traced) while persistence uses the torch state_dict default saver/loader.
"""

from typing import List

import pandas as pd
import torch
import torch.nn as nn
from sklearn.datasets import load_digits

from unionml_tpu import Dataset, Model

dataset = Dataset(name="{{app_name}}_dataset", test_size=0.2, shuffle=True, targets=["target"])


class DigitsMLP(nn.Module):
    def __init__(self, in_dims: int = 64, hidden_dims: int = 100, num_classes: int = 10):
        super().__init__()
        self.layers = nn.Sequential(
            nn.Linear(in_dims, hidden_dims), nn.ReLU(), nn.Linear(hidden_dims, num_classes)
        )

    def forward(self, features):
        return self.layers(features)


model = Model(name="{{app_name}}", init=DigitsMLP, dataset=dataset)


@dataset.reader
def reader() -> pd.DataFrame:
    return load_digits(as_frame=True).frame


@model.trainer
def trainer(
    module: DigitsMLP,
    features: pd.DataFrame,
    target: pd.DataFrame,
    *,
    batch_size: int = 512,
    n_epochs: int = 30,
    learning_rate: float = 3e-4,
) -> DigitsMLP:
    opt = torch.optim.Adam(module.parameters(), lr=learning_rate)
    loss_fn = nn.CrossEntropyLoss()
    X = torch.tensor(features.values, dtype=torch.float32)
    y = torch.tensor(target.squeeze().values, dtype=torch.long)
    for _ in range(n_epochs):
        for start in range(0, len(X), batch_size):
            opt.zero_grad()
            loss = loss_fn(module(X[start : start + batch_size]), y[start : start + batch_size])
            loss.backward()
            opt.step()
    return module


@model.predictor
def predictor(module: DigitsMLP, features: pd.DataFrame) -> List[float]:
    with torch.no_grad():
        logits = module(torch.tensor(features.values, dtype=torch.float32))
    return [float(x) for x in logits.argmax(dim=1)]


@model.evaluator
def evaluator(module: DigitsMLP, features: pd.DataFrame, target: pd.DataFrame) -> float:
    from sklearn.metrics import accuracy_score

    return float(accuracy_score(target.squeeze(), predictor(module, features)))


if __name__ == "__main__":
    module, metrics = model.train(hyperparameters={"in_dims": 64, "hidden_dims": 100, "num_classes": 10})
    print(f"metrics: {metrics}")
    model.save("torch_model.pt")
