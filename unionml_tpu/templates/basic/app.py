"""{{app_name}}: sklearn digits classifier on unionml-tpu (the quickstart)."""

from typing import List

import pandas as pd
from sklearn.datasets import load_digits
from sklearn.linear_model import LogisticRegression

from unionml_tpu import Dataset, Model

dataset = Dataset(name="{{app_name}}_dataset", test_size=0.2, shuffle=True, targets=["target"])
model = Model(name="{{app_name}}", init=LogisticRegression, dataset=dataset)


@dataset.reader
def reader() -> pd.DataFrame:
    return load_digits(as_frame=True).frame


@model.trainer
def trainer(estimator: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame) -> LogisticRegression:
    return estimator.fit(features, target.squeeze())


@model.predictor
def predictor(estimator: LogisticRegression, features: pd.DataFrame) -> List[float]:
    return [float(x) for x in estimator.predict(features)]


@model.evaluator
def evaluator(estimator: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame) -> float:
    from sklearn.metrics import accuracy_score

    return float(accuracy_score(target.squeeze(), estimator.predict(features)))


if __name__ == "__main__":
    model_object, metrics = model.train(hyperparameters={"C": 1.0, "max_iter": 5000})
    print(f"metrics: {metrics}")
    model.save("model.joblib")
    features = load_digits(as_frame=True).frame.sample(5, random_state=42).drop(columns=["target"])
    print(f"predictions: {model.predict(features=features.to_dict(orient='records'))}")
