"""{{app_name}}: the unionml-tpu quickstart.

Digits classification with a from-scratch jax softmax regression: the trainer
is a jit-compiled gradient loop, so the same app runs unchanged on CPU or a
TPU chip. (For the framework's batteries-included MLP/fit() loop, see the
``jax-digits`` template. Opaque model objects work too — the docs quickstart
trains a classic sklearn estimator, and ``torch-digits`` a pytorch MLP.)
"""

from typing import Dict, List

import jax
import jax.numpy as jnp
import pandas as pd
from sklearn.datasets import load_digits

from unionml_tpu import Dataset, Model

dataset = Dataset(name="{{app_name}}_dataset", test_size=0.2, shuffle=True, targets=["target"])


def init(scale: float = 0.01, seed: int = 0) -> Dict[str, jax.Array]:
    """A (64 pixels -> 10 classes) softmax regression, as a plain param dict."""
    key = jax.random.PRNGKey(seed)
    return {
        "w": scale * jax.random.normal(key, (64, 10), dtype=jnp.float32),
        "b": jnp.zeros((10,), dtype=jnp.float32),
    }


model = Model(name="{{app_name}}", init=init, dataset=dataset)


@dataset.reader
def reader() -> pd.DataFrame:
    return load_digits(as_frame=True).frame


def _pixels(features: pd.DataFrame) -> jax.Array:
    return jnp.asarray(features.to_numpy(), jnp.float32) / 16.0  # digits are 4-bit


@jax.jit
def _epoch(params: Dict[str, jax.Array], pixels, labels, learning_rate):
    """One full-batch SGD step on the cross-entropy; compiled once, reused."""

    def loss_fn(p):
        logits = pixels @ p["w"] + p["b"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))

    loss, grads = jax.value_and_grad(loss_fn)(params)
    params = jax.tree_util.tree_map(lambda p, g: p - learning_rate * g, params, grads)
    return params, loss


@model.trainer
def trainer(
    params: Dict[str, jax.Array],
    features: pd.DataFrame,
    target: pd.DataFrame,
    *,
    learning_rate: float = 0.5,
    num_epochs: int = 120,
) -> Dict[str, jax.Array]:
    pixels = _pixels(features)
    labels = jnp.asarray(target.squeeze().to_numpy(), jnp.int32)
    for _ in range(num_epochs):
        params, loss = _epoch(params, pixels, labels, learning_rate)
    return params


@model.predictor
def predictor(params: Dict[str, jax.Array], features: pd.DataFrame) -> List[float]:
    logits = _pixels(features) @ params["w"] + params["b"]
    return [float(c) for c in jnp.argmax(logits, axis=-1)]


@model.evaluator
def evaluator(params: Dict[str, jax.Array], features: pd.DataFrame, target: pd.DataFrame) -> float:
    guesses = jnp.asarray(predictor(params, features), jnp.int32)
    truth = jnp.asarray(target.squeeze().to_numpy(), jnp.int32)
    return float(jnp.mean(guesses == truth))


if __name__ == "__main__":
    params, metrics = model.train(hyperparameters={"scale": 0.01, "seed": 0})
    print(f"metrics: {metrics}")
    model.save("model.joblib")
    sample = load_digits(as_frame=True).frame.sample(5, random_state=42).drop(columns=["target"])
    print(f"predictions: {model.predict(features=sample.to_dict(orient='records'))}")
