"""{{app_name}}: BERT-base text-classification fine-tune (the flagship config).

Data contract: the reader returns a dict of arrays (input_ids, attention_mask,
labels) — plug in your tokenizer of choice upstream. Training runs the compiled
fit() loop with step-level checkpointing; on a v5e-8 pass a mesh for data
parallelism (see the data-parallel template).
"""

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from unionml_tpu import Dataset, Model
from unionml_tpu.models import (
    BertConfig,
    BertForSequenceClassification,
    TrainState,
    create_train_state,
    fit,
    init_params,
    make_classifier_eval_step,
)

SEQ_LEN = 128

dataset = Dataset(name="{{app_name}}_dataset", test_size=0.1, targets=["labels"])

config = BertConfig.base(num_labels=2, dtype=jnp.bfloat16)
bert = BertForSequenceClassification(config)


def init(learning_rate: float = 2e-5, warmup_steps: int = 100) -> TrainState:
    variables = init_params(config, seq_len=SEQ_LEN)  # or import_hf_weights(...)
    return create_train_state(
        bert, variables, learning_rate=learning_rate, warmup_steps=warmup_steps, total_steps=10_000
    )


model = Model(name="{{app_name}}", init=init, dataset=dataset)


@dataset.reader
def reader(n: int = 1024, seed: int = 0) -> Dict[str, np.ndarray]:
    """Replace with your tokenized dataset; shapes: (n, SEQ_LEN) int32 + (n,) labels."""
    rng = np.random.default_rng(seed)
    return {
        "input_ids": rng.integers(0, config.vocab_size, size=(n, SEQ_LEN)).astype(np.int32),
        "attention_mask": np.ones((n, SEQ_LEN), dtype=np.int32),
        "labels": rng.integers(0, config.num_labels, size=(n,)).astype(np.int32),
    }


@model.trainer
def trainer(
    state: TrainState,
    features: Dict[str, np.ndarray],
    targets: Dict[str, np.ndarray],
    *,
    num_epochs: int = 3,
    batch_size: int = 32,
    checkpoint_dir: str = "checkpoints",
) -> TrainState:
    data = {**features, **targets}
    result = fit(
        state,
        data,
        batch_size=batch_size,
        num_epochs=num_epochs,
        input_signature=("input_ids", "attention_mask"),
        checkpoint_dir=checkpoint_dir,
        log_every=50,
    )
    print(f"throughput: {result.examples_per_s:.1f} examples/s")
    return result.state


@model.predictor
def predictor(state: TrainState, features: Dict[str, np.ndarray]) -> jax.Array:
    logits = state.apply_fn(
        {"params": state.params},
        jnp.asarray(features["input_ids"]),
        jnp.asarray(features["attention_mask"]),
        deterministic=True,
    )
    return jnp.argmax(logits, axis=-1)


@model.evaluator
def evaluator(state: TrainState, features: Dict[str, np.ndarray], targets: Dict[str, np.ndarray]) -> float:
    metrics = make_classifier_eval_step(input_signature=("input_ids", "attention_mask"))(
        state,
        {
            "input_ids": jnp.asarray(features["input_ids"]),
            "attention_mask": jnp.asarray(features["attention_mask"]),
            "labels": jnp.asarray(targets["labels"]),
        },
    )
    return float(metrics["accuracy"])


if __name__ == "__main__":
    state, metrics = model.train(trainer_kwargs={"num_epochs": 1, "batch_size": 32})
    print(f"metrics: {metrics}")
    model.save("bert_model.ckpt")
