"""Project templates + a minimal renderer (cookiecutter replacement).

Reference parity: the five cookiecutter scaffolds under ``unionml/templates/`` with
shared pre/post hooks (name validation; git init of the generated app —
``templates/common/hooks/pre_gen_project.py:4-12``, ``post_gen_project.py:7-9``).
Rendering is plain ``{{app_name}}`` substitution in paths and contents.
"""

import subprocess
from pathlib import Path
from typing import List

TEMPLATES_ROOT = Path(__file__).parent

_DESCRIPTIONS = {
    "basic": "digits quickstart: from-scratch jax softmax regression + HTTP serving",
    "jax-digits": "jax-native digits MLP with a jit-compiled trainer",
    "mnist-cnn": "CNN image classifier trained with the compiled fit() loop",
    "bert-finetune": "BERT-base text classification fine-tune with checkpointing",
    "data-parallel": "data-parallel training over a TPU mesh (v5e-8 layout)",
    "serverless": "digits classifier behind a FaaS event handler",
    "bentoml-serving": "digits classifier packaged + served through bentoml build",
    "torch-digits": "pytorch MLP digits classifier (opaque-trainer path)",
    "keras-mnist": "Keras MNIST CNN (the reference tutorial recipe, opaque path)",
    "gpt-textgen": "character-level GPT text generation with KV-cache decoding",
    "moe-textgen": "sparse (mixture-of-experts) GPT text generation with router aux losses",
    "packed-textgen": "packed-sequence GPT training (fit_lm(pack=True)) + generation",
}


def list_templates() -> List[str]:
    return sorted(
        d.name for d in TEMPLATES_ROOT.iterdir() if d.is_dir() and not d.name.startswith("_")
    )


def template_description(name: str) -> str:
    return _DESCRIPTIONS.get(name, "")


def _validate_app_name(app_name: str) -> None:
    """Pre-generation guard: the app name must be an importable module name."""
    if not app_name.replace("_", "a").isalnum() or not app_name[0].isalpha():
        raise ValueError(
            f"app name {app_name!r} must be a valid Python identifier (letters, digits, underscores)"
        )


def render_template(name: str, app_name: str, destination: Path) -> Path:
    """Render a template into ``destination/app_name`` and git-init it.

    The git init matters: app versions are git shas (``unionml_tpu.remote.get_app_version``).
    """
    _validate_app_name(app_name)
    source = TEMPLATES_ROOT / name
    if not source.is_dir():
        raise ValueError(f"Unknown template {name!r}; available: {list_templates()}")
    target_root = destination / app_name
    if target_root.exists():
        raise FileExistsError(f"{target_root} already exists")

    for path in sorted(source.rglob("*")):
        rel = path.relative_to(source)
        rendered_rel = Path(str(rel).replace("{{app_name}}", app_name))
        target = target_root / rendered_rel
        if path.is_dir():
            target.mkdir(parents=True, exist_ok=True)
        else:
            target.parent.mkdir(parents=True, exist_ok=True)
            target.write_text(path.read_text().replace("{{app_name}}", app_name))

    try:
        subprocess.run(["git", "init", "-q"], cwd=target_root, check=True)
        subprocess.run(["git", "add", "-A"], cwd=target_root, check=True)
    except (subprocess.CalledProcessError, FileNotFoundError):
        pass  # git unavailable: versioning falls back to explicit app_version
    return target_root
