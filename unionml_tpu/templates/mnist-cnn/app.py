"""{{app_name}}: CNN image classifier (the Keras-MNIST tutorial shape, compiled).

The reader returns a dict of arrays: images (n, 28, 28) float32 and labels (n,).
Swap the synthetic reader for your MNIST loader of choice.
"""

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from unionml_tpu import Dataset, Model
from unionml_tpu.models import CNNClassifier, TrainState, create_train_state, fit, make_classifier_eval_step

dataset = Dataset(name="{{app_name}}_dataset", test_size=0.2, targets=["labels"])

cnn = CNNClassifier(num_classes=10)


def init(learning_rate: float = 3e-4) -> TrainState:
    params = cnn.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))
    return create_train_state(cnn, params, learning_rate=learning_rate)


model = Model(name="{{app_name}}", init=init, dataset=dataset)


@dataset.reader
def reader(n: int = 2048, seed: int = 0) -> Dict[str, np.ndarray]:
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n)
    images = rng.normal(size=(n, 28, 28)).astype(np.float32) + labels[:, None, None] * 0.1
    return {"images": images, "labels": labels.astype(np.int32)}


@model.trainer
def trainer(
    state: TrainState,
    features: Dict[str, np.ndarray],
    targets: Dict[str, np.ndarray],
    *,
    num_epochs: int = 10,
    batch_size: int = 512,
) -> TrainState:
    data = {"inputs": features["images"], "labels": targets["labels"]}
    return fit(state, data, batch_size=batch_size, num_epochs=num_epochs, log_every=100).state


@model.predictor
def predictor(state: TrainState, features: Dict[str, np.ndarray]) -> jax.Array:
    logits = state.apply_fn({"params": state.params}, jnp.asarray(features["images"]))
    return jnp.argmax(logits, axis=-1)


@model.evaluator
def evaluator(state: TrainState, features: Dict[str, np.ndarray], targets: Dict[str, np.ndarray]) -> float:
    metrics = make_classifier_eval_step()(
        state, {"inputs": jnp.asarray(features["images"]), "labels": jnp.asarray(targets["labels"])}
    )
    return float(metrics["accuracy"])


if __name__ == "__main__":
    state, metrics = model.train(hyperparameters={"learning_rate": 3e-4})
    print(f"metrics: {metrics}")
    model.save("cnn_model.ckpt")
