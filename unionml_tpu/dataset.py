"""Dataset: declarative spec for reading, splitting, parsing, and featurizing data.

Reference parity: ``unionml/dataset.py:43-527`` — the same six functional slots
(``reader`` required; ``loader``/``splitter``/``parser``/``feature_loader``/
``feature_transformer`` defaulted), the same pandas-aware default pipeline, dynamic
kwargs dataclasses, a ``dataset_task`` stage factory, and SQL constructors.

TPU-native deltas:

- the default pipeline understands arrays and dicts-of-arrays in addition to DataFrames,
  and can emit device arrays directly (``device_format="jax"``) so parsed splits land on
  the accelerator ready for a jit-compiled trainer;
- the splitter doubles as the shard-spec source for data parallelism: ``batch_sharding``
  names the logical batch axis consumed by :mod:`unionml_tpu.parallel` when laying data
  onto a device mesh (SURVEY.md §2 row 2).
"""

import json
from collections import OrderedDict
from enum import Enum
from functools import partial
from inspect import Parameter, signature
from pathlib import Path
from typing import Any, Callable, Dict, Generic, List, NamedTuple, Optional, Tuple, Type, TypeVar, get_args

import numpy as np
import pandas as pd

from unionml_tpu import type_guards
from unionml_tpu.defaults import DEFAULT_RESOURCES
from unionml_tpu.stage import Stage, stage
from unionml_tpu.tracker import TrackedInstance
from unionml_tpu.utils import kwargs_field_specs, make_json_dataclass, to_device_arrays

_EMPTY = Parameter.empty

DT = TypeVar("DT")
FT = TypeVar("FT")


class FeatureTypeUnion(Generic[DT, FT]):
    """Marker type for a feature slot fed by either the dataset type or loader output.

    Reference parity: ``unionml/dataset.py:30``.
    """


class DatasetTypeSource(Enum):
    """Which slot the materialized dataset type derives from (``dataset.py:34-40``)."""

    READER = "reader"
    LOADER = "loader"


class Dataset(TrackedInstance):
    """Specification of the data used to train and serve a model."""

    def __init__(
        self,
        name: str = "dataset",
        *,
        features: Optional[List[str]] = None,
        targets: Optional[List[str]] = None,
        test_size: float = 0.2,
        shuffle: bool = True,
        random_state: int = 12345,
        device_format: Optional[str] = None,
        batch_axis: str = "batch",
    ):
        """
        :param features: column/key names selecting feature data.
        :param targets: column/key names selecting target data.
        :param test_size: fraction of rows held out as the test split.
        :param shuffle: shuffle rows before splitting.
        :param random_state: seed for the shuffle.
        :param device_format: if ``"jax"``, parsed splits and transformed features are
            converted to device arrays (bfloat16-friendly float32) before they reach the
            trainer/predictor; ``None`` keeps host-native types (sklearn parity).
        :param batch_axis: logical name of the batch dimension, consumed by the
            data-parallel engine when sharding batches over a mesh.
        """
        super().__init__()
        self.name = name
        self._features = [] if features is None else list(features)
        self._targets = targets
        self._test_size = test_size
        self._shuffle = shuffle
        self._random_state = random_state
        self._device_format = device_format
        self.batch_axis = batch_axis

        self._loader: Callable = self._default_loader
        self._splitter: Callable = self._default_splitter
        self._parser: Callable = self._default_parser
        self._parser_feature_key: int = 0
        self._feature_loader: Callable = self._default_feature_loader
        self._feature_transformer: Callable = self._default_feature_transformer

        self._reader: Optional[Callable] = None
        self._reader_stage_kwargs: Optional[Dict[str, Any]] = None
        self._reader_input_parameters: Optional[List[Parameter]] = None
        self._materialized_datatype: Optional[Dict[str, Type]] = None
        self._dataset_stage: Optional[Stage] = None

        self._loader_kwargs_type: Optional[Type] = None
        self._splitter_kwargs_type: Optional[Type] = None
        self._parser_kwargs_type: Optional[Type] = None

    # ------------------------------------------------------------------ decorators

    def reader(self, fn: Optional[Callable] = None, **reader_stage_kwargs):
        """Register the function that fetches raw data from an external source."""
        if fn is None:
            return partial(self.reader, **reader_stage_kwargs)
        type_guards.guard_reader(fn)
        self._reader = fn
        self._reader_stage_kwargs = {"requests": DEFAULT_RESOURCES, "limits": DEFAULT_RESOURCES, **reader_stage_kwargs}
        return fn

    def loader(self, fn: Callable) -> Callable:
        """Register an optional function that loads raw reader output into memory."""
        type_guards.guard_loader(fn, self.dataset_datatype["data"])
        self._loader = fn
        self._loader_kwargs_type = None
        return fn

    def splitter(self, fn: Callable) -> Callable:
        """Register an optional function that partitions data into train/test splits."""
        type_guards.guard_splitter(fn, self.dataset_datatype["data"], self.dataset_datatype_source.value)
        self._splitter = fn
        self._splitter_kwargs_type = None
        return fn

    def parser(self, fn: Optional[Callable] = None, feature_key: int = 0):
        """Register an optional function producing (features, targets) from a split."""
        if fn is None:
            return partial(self.parser, feature_key=feature_key)
        type_guards.guard_parser(fn, self.dataset_datatype["data"], self.dataset_datatype_source.value)
        self._parser = fn
        self._parser_feature_key = feature_key
        self._parser_kwargs_type = None
        return fn

    def feature_loader(self, fn: Callable) -> Callable:
        """Register an optional function deserializing raw features (CLI / HTTP predict path)."""
        type_guards.guard_feature_loader(fn, Any)
        self._feature_loader = fn
        return fn

    def feature_transformer(self, fn: Callable) -> Callable:
        """Register an optional pre-processing function applied to features before prediction."""
        return_annotation = signature(self._feature_loader).return_annotation
        type_guards.guard_feature_transformer(fn, return_annotation)
        self._feature_transformer = fn
        return fn

    # ------------------------------------------------------------------ kwargs plumbing

    @property
    def splitter_kwargs(self) -> Dict[str, Any]:
        return {"test_size": self._test_size, "shuffle": self._shuffle, "random_state": self._random_state}

    @property
    def parser_kwargs(self) -> Dict[str, Any]:
        return {"features": self._features, "targets": self._targets}

    @property
    def loader_kwargs_type(self) -> Type:
        """JSON-able dataclass of the loader's trailing kwargs (``dataset.py:240-252``)."""
        if self._loader_kwargs_type is None:
            self._loader_kwargs_type = make_json_dataclass("LoaderKwargs", kwargs_field_specs(self._loader))
        return self._loader_kwargs_type

    @property
    def splitter_kwargs_type(self) -> Type:
        if self._splitter_kwargs_type is None:
            self._splitter_kwargs_type = make_json_dataclass(
                "SplitterKwargs", kwargs_field_specs(self._splitter, self.splitter_kwargs)
            )
        return self._splitter_kwargs_type

    @property
    def parser_kwargs_type(self) -> Type:
        if self._parser_kwargs_type is None:
            self._parser_kwargs_type = make_json_dataclass(
                "ParserKwargs", kwargs_field_specs(self._parser, self.parser_kwargs)
            )
        return self._parser_kwargs_type

    # ------------------------------------------------------------------ stages & pipelines

    def dataset_task(self) -> Stage:
        """Build (once) the stage that materializes raw data via the reader."""
        if self._dataset_stage is not None:
            return self._dataset_stage
        if self._reader is None:
            raise ValueError(f"Dataset {self.name!r} has no reader. Register one with @dataset.reader.")

        reader_sig = signature(self._reader)
        reader_output = NamedTuple("ReaderOutput", data=reader_sig.return_annotation)  # type: ignore[misc]

        @stage(
            unionml_obj=self,
            input_parameters=reader_sig.parameters,
            return_annotation=reader_output,
            **(self._reader_stage_kwargs or {}),
        )
        def dataset_task(**kwargs):
            return self._reader(**kwargs)

        self._dataset_stage = dataset_task
        return dataset_task

    def get_data(
        self,
        raw_data: Any,
        loader_kwargs: Optional[Dict[str, Any]] = None,
        splitter_kwargs: Optional[Dict[str, Any]] = None,
        parser_kwargs: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, List[Any]]:
        """Run raw data through loader -> splitter -> parser -> feature_transformer.

        Returns ``{"train": [...], "test": [...]}`` (test omitted for single-split
        splitters). Reference parity: ``unionml/dataset.py:302-348``.
        """
        merged_loader = {**({} if loader_kwargs is None else loader_kwargs)}
        merged_splitter = {**self.splitter_kwargs, **({} if splitter_kwargs is None else splitter_kwargs)}
        merged_parser = {**self.parser_kwargs, **({} if parser_kwargs is None else parser_kwargs)}

        data = self._loader(raw_data, **merged_loader)
        splits = self._splitter(data, **merged_splitter)

        out: Dict[str, List[Any]] = {}
        split_names = ["train", "test", "validation"]
        for split_name, split in zip(split_names, splits):
            parsed = [*self._parser(split, **merged_parser)]
            parsed[self._parser_feature_key] = self._feature_transformer(parsed[self._parser_feature_key])
            if self._device_format == "jax":
                parsed = list(to_device_arrays(*parsed))
            out[split_name] = parsed
        return out

    def get_features(self, features: Any) -> Any:
        """Run raw features through feature_loader -> feature_transformer (``dataset.py:350-359``)."""
        features = self._feature_loader(features)
        return self.finalize_features(self._feature_transformer(features))

    def finalize_features(self, features: Any) -> Any:
        """Apply the device-format conversion to transformed features.

        Called by every path that hands features to the predictor (``get_features`` and
        the predict-from-reader task) so both agree on the on-device representation.
        """
        if self._device_format == "jax":
            (features,) = to_device_arrays(features)
        return features

    # ------------------------------------------------------------------ type derivation

    @property
    def reader_input_types(self) -> Optional[List[Parameter]]:
        if self._reader is not None and self._reader_input_parameters is None:
            return [*signature(self._reader).parameters.values()]
        return self._reader_input_parameters

    @property
    def dataset_datatype(self) -> Dict[str, Type]:
        """Materialized dataset type; loader return annotation wins over reader's."""
        if self._loader != self._default_loader:
            return {"data": signature(self._loader).return_annotation}
        if self._reader is not None and self._materialized_datatype is None:
            return {"data": signature(self._reader).return_annotation}
        if self._materialized_datatype is not None:
            return self._materialized_datatype
        raise ValueError(
            "dataset datatype is undefined: register a @dataset.reader function with a return annotation."
        )

    @property
    def dataset_datatype_source(self) -> DatasetTypeSource:
        return DatasetTypeSource.LOADER if self._loader != self._default_loader else DatasetTypeSource.READER

    @property
    def parser_return_types(self) -> Tuple[Any, ...]:
        return get_args(signature(self._parser).return_annotation)

    @property
    def feature_type(self) -> Type:
        """Type of the features accepted by the predictor (``dataset.py:398-424``).

        TPU-native: with ``device_format="jax"`` the pipeline converts features to
        device arrays, so the predictor contract is ``jax.Array`` regardless of the
        host-side reader type.
        """
        if self._device_format == "jax":
            import jax
            from typing import get_origin

            if self._feature_loader != self._default_feature_loader:
                # a custom loader returning a DICT defines a multi-input feature
                # structure (tokenized models); device conversion preserves it.
                # Loaders annotated with host-side types (DataFrame, lists) keep the
                # jax.Array contract — conversion flattens them to a device array.
                annotation = signature(self._feature_loader).return_annotation
                if annotation is not Parameter.empty and get_origin(annotation) is dict:
                    return annotation
            return jax.Array
        dataset_type = (
            self.dataset_datatype["data"]
            if self._parser == self._default_parser
            else self.parser_return_types[self._parser_feature_key]
        )
        loaded_type = (
            signature(self._feature_loader).return_annotation
            if self._feature_transformer == self._default_feature_transformer
            else signature(self._feature_transformer).return_annotation
        )
        if self._feature_loader == self._default_feature_loader:
            return dataset_type
        if dataset_type != loaded_type:
            return FeatureTypeUnion[dataset_type, loaded_type]  # type: ignore[index]
        return dataset_type

    # ------------------------------------------------------------------ SQL constructors

    @classmethod
    def from_sqlite(
        cls,
        db_path: str,
        query: str,
        *,
        query_params: Optional[Dict[str, Type]] = None,
        **dataset_kwargs: Any,
    ) -> "Dataset":
        """Create a Dataset whose reader executes a SQLite query.

        Reference parity: ``Dataset.from_sqlite_task`` (``unionml/dataset.py:442-455``)
        built on flytekit's SQLite3Task; here the reader uses the stdlib ``sqlite3``
        driver with named-placeholder parameters (``:param`` syntax).
        """
        dataset = cls(**dataset_kwargs)

        params = query_params or {}

        def sqlite_reader(**kwargs) -> pd.DataFrame:
            import sqlite3

            with sqlite3.connect(db_path) as conn:
                return pd.read_sql_query(query, conn, params=kwargs or None)

        sqlite_reader.__signature__ = signature(sqlite_reader).replace(  # type: ignore[attr-defined]
            parameters=[Parameter(k, Parameter.KEYWORD_ONLY, annotation=v) for k, v in params.items()],
            return_annotation=pd.DataFrame,
        )
        sqlite_reader.__annotations__ = {**{k: v for k, v in params.items()}, "return": pd.DataFrame}
        dataset.reader(sqlite_reader)
        return dataset

    @classmethod
    def from_sqlalchemy(
        cls,
        url: str,
        query: str,
        *,
        query_params: Optional[Dict[str, Type]] = None,
        **dataset_kwargs: Any,
    ) -> "Dataset":
        """Create a Dataset whose reader executes a query against a SQLAlchemy URL.

        Reference parity: ``Dataset.from_sqlalchemy_task`` (``dataset.py:457-470``).
        Requires the optional ``sqlalchemy`` package.
        """
        dataset = cls(**dataset_kwargs)
        params = query_params or {}

        def sqlalchemy_reader(**kwargs) -> pd.DataFrame:
            import sqlalchemy

            engine = sqlalchemy.create_engine(url)
            with engine.connect() as conn:
                return pd.read_sql_query(sqlalchemy.text(query), conn, params=kwargs or None)

        sqlalchemy_reader.__signature__ = signature(sqlalchemy_reader).replace(  # type: ignore[attr-defined]
            parameters=[Parameter(k, Parameter.KEYWORD_ONLY, annotation=v) for k, v in params.items()],
            return_annotation=pd.DataFrame,
        )
        sqlalchemy_reader.__annotations__ = {**{k: v for k, v in params.items()}, "return": pd.DataFrame}
        dataset.reader(sqlalchemy_reader)
        return dataset

    # ------------------------------------------------------------------ defaults

    def _default_loader(self, data: Any) -> Any:
        """Coerce raw reader output into the declared dataset type (``dataset.py:472-476``)."""
        [(_, declared)] = self.dataset_datatype.items()
        if declared is pd.DataFrame and not isinstance(data, pd.DataFrame):
            return pd.DataFrame(data)
        return data

    def _default_splitter(self, data: Any, test_size: float, shuffle: bool, random_state: int) -> Tuple[Any, ...]:
        """Shuffle + hold out ``test_size`` of rows.

        Handles DataFrames, arrays, and dicts of same-length arrays; any other type
        passes through as a single train split (``dataset.py:478-487`` behavior).
        """
        if isinstance(data, pd.DataFrame):
            n_rows = len(data)
        elif isinstance(data, np.ndarray):
            n_rows = data.shape[0]
        elif isinstance(data, dict) and data and all(hasattr(v, "__len__") for v in data.values()):
            n_rows = len(next(iter(data.values())))
        else:
            return (data,)

        n_test = int(n_rows * test_size)
        indices = np.arange(n_rows)
        if shuffle:
            indices = np.random.default_rng(random_state).permutation(n_rows)
        train_idx, test_idx = indices[: n_rows - n_test], indices[n_rows - n_test :]

        def take_rows(value, subset):
            if isinstance(value, (list, tuple)):
                try:
                    array = np.asarray(value)
                except ValueError:
                    array = np.empty(0, dtype=object)
                if array.dtype == object:
                    # only RAGGED columns (variable-length token sequences for
                    # packed LM training) stay python lists; rectangular list
                    # columns keep becoming arrays as they always have
                    return [value[i] for i in subset]
                return array[subset]
            return np.asarray(value)[subset]

        def take(subset):
            if isinstance(data, pd.DataFrame):
                return data.iloc[subset]
            if isinstance(data, np.ndarray):
                return data[subset]
            return {k: take_rows(v, subset) for k, v in data.items()}

        return take(train_idx), take(test_idx)

    def _default_parser(
        self, data: Any, features: Optional[List[str]], targets: Optional[List[str]]
    ) -> Tuple[Any, Any]:
        """Select feature/target columns from a DataFrame or dict (``dataset.py:489-504``)."""
        if isinstance(data, dict):
            feature_keys = features or [k for k in data if k not in (targets or [])]
            feature_data = {k: data[k] for k in feature_keys}
            target_data = {k: data[k] for k in (targets or []) if k in data}
            return feature_data, target_data
        if not isinstance(data, pd.DataFrame):
            return (data,)  # type: ignore[return-value]

        if not features:
            features = [col for col in data.columns if col not in (targets or [])]
        try:
            target_data = data[targets] if targets else pd.DataFrame()
        except KeyError:
            target_data = pd.DataFrame()
        return data[features], target_data

    def _default_feature_loader(self, features: Any) -> Any:
        """Load features from a path / JSON / records into the dataset type (``dataset.py:506-520``)."""
        if isinstance(features, Path):
            with features.open() as f:
                features = json.load(f)

        [(_, declared)] = self.dataset_datatype.items()
        if declared is pd.DataFrame:
            data = pd.DataFrame(features)
            feature_names = self._features
            if not feature_names and self._targets is not None:
                feature_names = [col for col in data.columns if col not in self._targets]
            return data[feature_names] if feature_names else data
        return features

    def _default_feature_transformer(self, features: Any) -> Any:
        return features
