"""Hot ops owned by the framework: attention kernels, fused losses, and
weight-only int8 quantization."""

from unionml_tpu.ops.attention import attention, flash_attention, xla_attention
from unionml_tpu.ops.losses import (
    accuracy,
    cross_entropy_and_accuracy,
    cross_entropy_with_integer_labels,
)
from unionml_tpu.ops.quant import (
    QuantizedArray,
    dequantize_tree,
    quantize_array,
    quantize_tree,
    quantized_bytes,
)

__all__ = [
    "QuantizedArray",
    "accuracy",
    "attention",
    "cross_entropy_and_accuracy",
    "cross_entropy_with_integer_labels",
    "dequantize_tree",
    "flash_attention",
    "quantize_array",
    "quantize_tree",
    "quantized_bytes",
    "xla_attention",
]
