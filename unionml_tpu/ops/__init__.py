"""Hot ops owned by the framework: attention kernels and fused losses."""

from unionml_tpu.ops.attention import attention, flash_attention, xla_attention
from unionml_tpu.ops.losses import (
    accuracy,
    cross_entropy_and_accuracy,
    cross_entropy_with_integer_labels,
)

__all__ = [
    "accuracy",
    "attention",
    "cross_entropy_and_accuracy",
    "cross_entropy_with_integer_labels",
    "flash_attention",
    "xla_attention",
]
