"""Token-sampling transforms for batched decoding (temperature, top-k, top-p).

All transforms are per-row over ``(batch, vocab)`` logits with PER-ROW controls,
so one compiled program serves slots with heterogeneous request settings (the
decode engine batches requests with different sampling params into one step).
Disabled rows pass through untouched: ``top_k == 0`` and ``top_p >= 1`` are
no-ops, ``temperature == 0`` selects greedy argmax.

TPU notes: filtering uses one descending sort of the logits row (vocab-sized,
vectorized — microseconds next to the decode matmuls) and masks with ``-inf``,
which ``jax.random.categorical`` (Gumbel argmax) never selects. Everything is
shape-static and branch-free, so the program is identical for any mix of
settings; only the *values* change per step.

Reference surface: the reference (unionai-oss/unionml) has no generation
sampling — this mirrors the standard text-generation serving contract
(HF ``generate``'s ``temperature`` / ``top_k`` / ``top_p``) the TPU build's
GPT family and ``/generate`` route provide.
"""

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["apply_top_k", "apply_top_p", "sample_logits", "validate_sampling"]


def validate_sampling(temperature=None, top_k=0, top_p=1.0):
    """Validate and normalize the sampling contract shared by every entry point
    (HTTP route, ``DecodeEngine.add_request``, ``models.gpt.generate``).

    ``temperature=None`` passes through (the caller's default applies).
    :returns: ``(temperature, top_k, top_p)`` as ``(Optional[float], int, float)``.
    :raises ValueError: temperature < 0, top_k < 0, or top_p outside ``(0, 1]``.
    """
    if temperature is not None:
        if isinstance(temperature, bool):
            raise ValueError("temperature must be a number")
        temperature = float(temperature)
        if temperature < 0.0:
            raise ValueError("temperature must be >= 0")
    # booleans are ints in Python and int() truncates floats — both would turn a
    # malformed top_k into a silently different request instead of a 422
    if isinstance(top_k, bool):
        raise ValueError("top_k must be an integer")
    try:
        if int(top_k) != top_k:
            raise ValueError(f"top_k must be an integer, got {top_k!r}")
    except TypeError:
        raise ValueError(f"top_k must be an integer, got {top_k!r}")
    top_k = int(top_k)
    if top_k < 0:
        raise ValueError("top_k must be >= 0")
    if isinstance(top_p, bool):
        raise ValueError("top_p must be a number")
    top_p = float(top_p)
    if not 0.0 < top_p <= 1.0:
        raise ValueError("top_p must be in (0, 1]")
    return temperature, top_k, top_p


def apply_top_k(logits: jax.Array, top_k: jax.Array) -> jax.Array:
    """Mask each row to its ``top_k[i]`` highest logits (ties at the threshold kept).

    :param logits: ``(batch, vocab)``.
    :param top_k: ``(batch,)`` int; ``0`` disables the filter for that row.
    """
    vocab = logits.shape[-1]
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    k = jnp.clip(top_k, 1, vocab)
    kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None].astype(jnp.int32), axis=-1)
    keep = logits >= kth
    keep = jnp.where((top_k > 0)[:, None], keep, True)
    return jnp.where(keep, logits, -jnp.inf)


def apply_top_p(logits: jax.Array, top_p: jax.Array) -> jax.Array:
    """Nucleus filter: keep each row's smallest prefix of probability mass >= ``top_p[i]``.

    At least one token (the argmax) always survives. ``top_p >= 1`` disables the
    filter for that row.

    :param logits: ``(batch, vocab)``.
    :param top_p: ``(batch,)`` float in ``(0, 1]``.
    """
    probs = jax.nn.softmax(logits, axis=-1)
    sort_idx = jnp.argsort(-probs, axis=-1)  # descending, ties broken by index
    sorted_probs = jnp.take_along_axis(probs, sort_idx, axis=-1)
    cumulative = jnp.cumsum(sorted_probs, axis=-1)
    # a sorted position is kept while the mass BEFORE it is < top_p, so the
    # prefix always includes position 0 and stops once mass is covered
    keep_sorted = (cumulative - sorted_probs) < top_p[:, None]
    # scatter the sorted keep mask back through the sort permutation (HF-style):
    # a threshold comparison in unsorted space would also keep tokens OUTSIDE the
    # nucleus whose probability exactly ties the boundary (ADVICE round-2)
    inv_idx = jnp.argsort(sort_idx, axis=-1)
    keep = jnp.take_along_axis(keep_sorted, inv_idx, axis=-1)
    keep = jnp.where((top_p < 1.0)[:, None], keep, True)
    return jnp.where(keep, logits, -jnp.inf)


def sample_logits(
    logits: jax.Array,
    key: jax.Array,
    temperature: jax.Array,
    top_k: Optional[jax.Array] = None,
    top_p: Optional[jax.Array] = None,
) -> jax.Array:
    """Sample one token per row honoring per-row temperature / top-k / top-p.

    Rows with ``temperature == 0`` take the greedy argmax (of the raw logits);
    the rest sample from the filtered, temperature-scaled distribution.

    :param logits: ``(batch, vocab)``.
    :param key: PRNG key consumed for the whole batch.
    :param temperature: ``(batch,)`` float ``>= 0``.
    :returns: ``(batch,)`` int32 token ids.
    """
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    if top_k is not None:
        scaled = apply_top_k(scaled, top_k)
    if top_p is not None:
        scaled = apply_top_p(scaled, top_p)
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)
