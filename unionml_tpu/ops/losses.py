"""Fused loss ops: numerically stable cross-entropy with integer labels.

Written so XLA fuses the logsumexp chain into the final matmul's epilogue; keeps
logits in f32 regardless of the (bfloat16) compute dtype — the standard TPU mixed-
precision recipe.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def cross_entropy_with_integer_labels(
    logits: jax.Array,
    labels: jax.Array,
    weights: Optional[jax.Array] = None,
) -> jax.Array:
    """Mean (optionally weighted) softmax cross-entropy; labels are class indices.

    ``weights`` masks out entries (e.g. padding) and normalizes by total weight.
    """
    logits = logits.astype(jnp.float32)
    log_z = jax.scipy.special.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    losses = log_z - label_logits
    if weights is not None:
        weights = weights.astype(jnp.float32)
        # epsilon guards only the all-zero case; fractional weight sums stay exact
        return jnp.sum(losses * weights) / jnp.maximum(jnp.sum(weights), 1e-8)
    return jnp.mean(losses)


def accuracy(logits: jax.Array, labels: jax.Array, weights: Optional[jax.Array] = None) -> jax.Array:
    predictions = jnp.argmax(logits, axis=-1)
    correct = (predictions == labels).astype(jnp.float32)
    if weights is not None:
        weights = weights.astype(jnp.float32)
        return jnp.sum(correct * weights) / jnp.maximum(jnp.sum(weights), 1e-8)
    return jnp.mean(correct)


def cross_entropy_and_accuracy(
    logits: jax.Array, labels: jax.Array, weights: Optional[jax.Array] = None
) -> Tuple[jax.Array, jax.Array]:
    return (
        cross_entropy_with_integer_labels(logits, labels, weights),
        accuracy(logits, labels, weights),
    )
