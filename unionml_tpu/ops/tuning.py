"""Flash-attention block-size selection.

Mosaic tile choice is a measured quantity, not a guess: ``bench_kernels.py`` sweeps
``(block_q, block_k)`` on real hardware and records winners per shape class in
``KERNEL_BENCH.json`` at the repo root; the committed winners live in
:data:`TUNED_BLOCKS` below. Shapes without a measured entry fall back to the largest
candidate block that tiles the sequence (<= 128 until measurements justify bigger —
VERDICT round-1: "block sizes (128/128) are untuned guesses" — the guess is now
explicit, bounded, and overridden by data as it lands).

Shape class key: ``(seq_q, seq_k, head_dim)``.
"""

from typing import Dict, Tuple

#: measured winners — populated from bench_kernels.py runs on real TPU hardware.
#: Format: {(seq_q, seq_k, head_dim): (block_q, block_k)}
TUNED_BLOCKS: Dict[Tuple[int, int, int], Tuple[int, int]] = {
    # Measured on v5e via the ON-DEVICE scanned sweep (KERNEL_BENCH.json,
    # 2026-07-29T17:0xZ — per-launch timing over the remote tunnel bottoms out at
    # ~3.7ms regardless of shape and had produced bogus winners; see
    # bench_kernels.py and TPU_PROBES.log for the methodology note).
    (128, 128, 64): (128, 128),
    (256, 256, 64): (256, 256),
    (512, 512, 64): (256, 512),
    (1024, 1024, 64): (512, 512),
    (512, 512, 128): (512, 512),
}

#: measured pallas-vs-XLA verdicts per shape class (same sweep + the END-TO-END
#: arbiter: BERT-base train step on v5e ran 56.4ms/step with XLA attention vs
#: 69.8ms with pallas at B=64 S=128 — TPU_PROBES.log 2026-07-29). XLA's fused
#: attention wins or ties every measured practical shape on v5e; the pallas
#: kernels remain available via impl="pallas" and carry the tuned blocks above.
MEASURED_IMPL: Dict[Tuple[int, int, int], str] = {
    (128, 128, 64): "xla",
    (256, 256, 64): "xla",
    (512, 512, 64): "xla",
    (1024, 1024, 64): "xla",  # sweep margin <1% — a tie broken toward the default
    (512, 512, 128): "xla",
}

#: unmeasured shapes follow the measured trend on this hardware
DEFAULT_TPU_IMPL = "xla"


def pick_impl(seq_q: int, seq_k: int, head_dim: int) -> str:
    """Measured attention backend for a shape class ("xla" or "pallas")."""
    return MEASURED_IMPL.get((seq_q, seq_k, head_dim), DEFAULT_TPU_IMPL)


#: measured pallas-vs-XLA verdicts for PACKED (segment-ids) shapes. The regimes
#: differ structurally from the dense case: the XLA path must materialize a dense
#: (seq, seq) mask per row (O(seq^2) HBM write + read), while the kernel compares
#: segment ids blockwise in VMEM. Populated from ``bench_kernels.py --packed``
#: runs on real hardware (PACKED_KERNEL_BENCH.json).
MEASURED_PACKED_IMPL: Dict[Tuple[int, int, int], str] = {}

#: unmeasured packed shapes follow the measured dense-shape trend (XLA wins or
#: ties every measured practical shape on v5e). The kernel's structural edge —
#: no dense O(seq^2) mask — is plausible but UNMEASURED; an unmeasured default
#: must be the conservative one. A ``--packed`` sweep flips this per shape class.
DEFAULT_PACKED_IMPL = "xla"


def pick_packed_impl(seq_q: int, seq_k: int, head_dim: int) -> str:
    """Measured attention backend for a packed (segment-ids) shape class."""
    return MEASURED_PACKED_IMPL.get((seq_q, seq_k, head_dim), DEFAULT_PACKED_IMPL)


#: measured winners for PACKED (segment-ids) sweeps — kept separate from the
#: dense table: the segment-masked, block-skipping kernel has its own optimal
#: tiling, and a packed winner must never displace a dense one (or vice versa)
PACKED_TUNED_BLOCKS: Dict[Tuple[int, int, int], Tuple[int, int]] = {}

#: candidate block edges for the sweep and the fallback ladder
BLOCK_CANDIDATES: Tuple[int, ...] = (512, 256, 128, 64)

#: measured pallas-vs-XLA verdicts for the PAGED decode kernel
#: (:mod:`unionml_tpu.ops.paged_attention`). Shape class:
#: ``(table_width, block_size, heads, head_dim)`` — the four axes that fix the
#: kernel's grid and per-step DMA. Populated from ``bench_kernels.py --paged``
#: sweeps via the ``TUNING_MEASURED.json`` overlay (``tools/tpu_window.sh``
#: ``paged_attn`` phase).
MEASURED_PAGED_IMPL: Dict[Tuple[int, int, int, int], str] = {}

#: unmeasured paged shapes default to the KERNEL — deliberately the opposite of
#: the conservative dense default: the XLA arm's dense dequantized gather copy
#: is a modeled ~4x HBM write+read the kernel structurally never issues
#: (``paged_attention.gather_hbm_bytes`` vs ``fused_hbm_bytes``), so here the
#: burden of proof sits on XLA; a measured window demotes per shape class.
DEFAULT_PAGED_IMPL = "pallas"


def pick_paged_impl(table_width: int, block_size: int, heads: int, head_dim: int) -> str:
    """Measured paged-decode backend for a shape class ("pallas" or "xla")."""
    return MEASURED_PAGED_IMPL.get(
        (table_width, block_size, heads, head_dim), DEFAULT_PAGED_IMPL
    )


#: measured winners for the paged kernel's one tiling knob: heads folded into a
#: single grid step (amortizes grid/DMA overhead when blocks are small). 1 is
#: the proven-lowering default (plain 2D MXU dots); sweeps promote larger.
PAGED_TUNED_HEADS: Dict[Tuple[int, int, int, int], int] = {}


def pick_paged_heads(table_width: int, block_size: int, heads: int, head_dim: int) -> int:
    """Heads per grid step for a paged shape class (measured winner, else 1)."""
    tuned = PAGED_TUNED_HEADS.get((table_width, block_size, heads, head_dim))
    if tuned and heads % tuned == 0:
        return tuned
    return 1


def _largest_dividing(seq: int, cap: int = 128) -> int:
    for candidate in BLOCK_CANDIDATES:
        if candidate <= cap and seq % candidate == 0:
            return candidate
    if seq <= cap and seq % 8 == 0:
        return seq  # tiny but Mosaic-tileable (sublane multiple): one block
    # irregular or unalignable-at-cap sequences (seq % cap != 0 is guaranteed here —
    # a dividing cap would have been returned by the candidate loop): return the
    # non-dividing cap so the kernel's alignment check routes the call to the XLA
    # fallback instead of a doomed Mosaic compile (or a seq x seq tile over VMEM)
    return cap


def pick_block_sizes(
    seq_q: int, seq_k: int, head_dim: int, packed: bool = False
) -> Tuple[int, int]:
    """Block sizes for a flash-attention call: measured winner, else aligned default.

    ``packed=True`` consults the packed sweep's winners first (falling back to
    the dense winners, then the aligned ladder).
    """
    shape = (seq_q, seq_k, head_dim)
    if packed:
        tuned = PACKED_TUNED_BLOCKS.get(shape) or TUNED_BLOCKS.get(shape)
    else:
        tuned = TUNED_BLOCKS.get(shape)
    if tuned is not None:
        return tuned
    return _largest_dividing(seq_q), _largest_dividing(seq_k)


def _apply_measured_overlay() -> None:
    """Merge ``TUNING_MEASURED.json`` (repo root) over the static tables.

    The measurement battery (``tools/tpu_window.sh``) runs the kernel sweeps and
    then ``tools/promote_tuning.py``, which distills the sweep artifacts into
    this one overlay file — so a live hardware window updates the dispatch
    tables without hand-editing source, and the overlay is committed alongside
    the sweep JSONs it came from. Key format: ``"seq_q,seq_k,head_dim"``.
    """
    import json
    import os

    # Explicit env-var hook first, then the repo root (developer checkout). No
    # cwd fallback: a stale TUNING_MEASURED.json in an unrelated working
    # directory must not silently alter kernel dispatch (ADVICE round 4).
    candidates = [
        os.environ.get("UNIONML_TUNING_OVERLAY", ""),
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..", "TUNING_MEASURED.json"),
    ]
    overlay = None
    for path in candidates:
        if not path:
            continue
        try:
            with open(path) as fh:
                loaded = json.load(fh)
        except (OSError, ValueError):
            continue
        # valid JSON of the wrong type is as malformed as broken syntax: fall
        # through to the next candidate either way
        if isinstance(loaded, dict):
            overlay = loaded
            break
    if overlay is None:
        return

    def parse(table, rank=3):
        out = {}
        if not isinstance(table, dict):
            return out
        for key, value in table.items():
            try:
                shape = tuple(int(x) for x in key.split(","))
            except (AttributeError, ValueError):
                continue
            if len(shape) == rank:
                out[shape] = value
        return out

    def valid_impl(value):
        return value in ("xla", "pallas")

    def valid_blocks(value):
        return (
            isinstance(value, (list, tuple))
            and len(value) == 2
            and all(isinstance(b, int) and not isinstance(b, bool) and b > 0 for b in value)
        )

    # Malformed entries (wrong type, unknown impl, non-int blocks) are dropped
    # here rather than surfacing later as a confusing in-trace failure.
    for shape, impl in parse(overlay.get("measured_impl")).items():
        if valid_impl(impl):
            MEASURED_IMPL[shape] = impl
    for shape, impl in parse(overlay.get("measured_packed_impl")).items():
        if valid_impl(impl):
            MEASURED_PACKED_IMPL[shape] = impl
    for shape, blocks in parse(overlay.get("tuned_blocks")).items():
        if valid_blocks(blocks):
            TUNED_BLOCKS[shape] = tuple(blocks)
    for shape, blocks in parse(overlay.get("packed_tuned_blocks")).items():
        if valid_blocks(blocks):
            PACKED_TUNED_BLOCKS[shape] = tuple(blocks)
    # paged-decode kernel tables: 4-axis keys "table_width,block_size,heads,head_dim"
    for shape, impl in parse(overlay.get("measured_paged_impl"), rank=4).items():
        if valid_impl(impl):
            MEASURED_PAGED_IMPL[shape] = impl
    for shape, gh in parse(overlay.get("paged_tuned_heads"), rank=4).items():
        if isinstance(gh, int) and not isinstance(gh, bool) and gh > 0:
            PAGED_TUNED_HEADS[shape] = gh


_apply_measured_overlay()
