"""Flash-attention block-size selection.

Mosaic tile choice is a measured quantity, not a guess: ``bench_kernels.py`` sweeps
``(block_q, block_k)`` on real hardware and records winners per shape class in
``KERNEL_BENCH.json`` at the repo root; the committed winners live in
:data:`TUNED_BLOCKS` below. Shapes without a measured entry fall back to the largest
candidate block that tiles the sequence (<= 128 until measurements justify bigger —
VERDICT round-1: "block sizes (128/128) are untuned guesses" — the guess is now
explicit, bounded, and overridden by data as it lands).

Shape class key: ``(seq_q, seq_k, head_dim)``.
"""

from typing import Dict, Tuple

#: measured winners — populated from bench_kernels.py runs on real TPU hardware.
#: Format: {(seq_q, seq_k, head_dim): (block_q, block_k)}
TUNED_BLOCKS: Dict[Tuple[int, int, int], Tuple[int, int]] = {
    # Measured on v5e (axon tunnel window 2026-07-29T13:53Z, KERNEL_BENCH.json):
    # seq 128: only (128,128) tiles; fwd+bwd 12.35ms vs XLA 12.72ms -> pallas.
    # seq 512: (256,128) wins fwd+bwd 11.48ms vs XLA 14.63ms (fwd 4.43 vs 11.10).
    (128, 128, 64): (128, 128),
    (512, 512, 64): (256, 128),
}

#: candidate block edges for the sweep and the fallback ladder
BLOCK_CANDIDATES: Tuple[int, ...] = (512, 256, 128, 64)


def _largest_dividing(seq: int, cap: int = 128) -> int:
    for candidate in BLOCK_CANDIDATES:
        if candidate <= cap and seq % candidate == 0:
            return candidate
    if seq <= cap and seq % 8 == 0:
        return seq  # tiny but Mosaic-tileable (sublane multiple): one block
    # irregular or unalignable-at-cap sequences (seq % cap != 0 is guaranteed here —
    # a dividing cap would have been returned by the candidate loop): return the
    # non-dividing cap so the kernel's alignment check routes the call to the XLA
    # fallback instead of a doomed Mosaic compile (or a seq x seq tile over VMEM)
    return cap


def pick_block_sizes(seq_q: int, seq_k: int, head_dim: int) -> Tuple[int, int]:
    """Block sizes for a flash-attention call: measured winner, else aligned default."""
    tuned = TUNED_BLOCKS.get((seq_q, seq_k, head_dim))
    if tuned is not None:
        return tuned
    return _largest_dividing(seq_q), _largest_dividing(seq_k)
