"""Paged-attention decode: fused Pallas dequant-attend straight off the block pool.

The paged serving path (PRs 11/14) keeps every slot's KV in a shared block pool
— int8 codes plus per-(block, head) scales under ``kv_quantize`` — and the XLA
decode step pays a ``pool[table]`` gather that materializes a dense, dequantized
KV copy before attending (``models/gpt.py`` ``gather_table``). On real HBM that
copy is ~4x the bytes the int8 codes occupy, per step, per layer. The kernel
here deletes it: each grid step DMAs ONE pool block's codes (+ its scales)
straight out of HBM via the slot's block-table row (scalar-prefetched, so the
index feeds the DMA engine), dequantizes in VMEM, and folds the block into an
online-softmax accumulation — flash-decoding over the table indirection. HBM
traffic per step is the int8 codes + scales; the bf16-pool variant simply skips
the dequant.

Two implementations behind one dispatcher (the ``ops/attention.py`` contract):

- ``impl="pallas"``: the fused kernel. Grid ``(batch, head_groups, width)`` with
  the table walk innermost; VMEM scratch carries the (m, l, acc) softmax state
  across blocks, initialized at ``w == 0`` and normalized/written at the last
  block.
- ``impl="xla"``: gather-dequant-attend, arithmetic-identical to the historical
  ``gather_table`` + ``xla_attention`` path (the reference the kernel is pinned
  against, and the fallback off-TPU).
- ``impl="auto"``: pallas on TPU, XLA elsewhere. Unlike the dense-attention
  tables (where XLA's fused attention measured ahead), the paged default is
  pallas: the XLA arm's dense dequant copy is a modeled ~4x HBM write+read the
  kernel provably never issues (see :func:`fused_hbm_bytes` /
  :func:`gather_hbm_bytes`), and a measured verdict per shape class
  (:func:`unionml_tpu.ops.tuning.pick_paged_impl`, ``TUNING_MEASURED.json``)
  overrides the default as windows land.

Layout contract (matches ``init_block_pool``): pool leaves are
``(num_blocks, heads, block_size, head_dim)``; scales ``(num_blocks, heads, 1,
1)`` f32; ``block_table`` is ``(batch, width)`` int32; a query token at logical
position ``p`` attends keys at logical positions ``k <= p``, where logical
column ``c = w * block_size + o`` lives in pool block ``table[row, w]``. Table
columns past a row's live range point at the engine's scratch block — their
positions exceed every live query position, so the mask discards them without
any per-row length plumbing.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from unionml_tpu.ops.attention import on_tpu, xla_attention

_NEG_INF = -1e30


def xla_paged_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_table: jax.Array,
    base_positions: jax.Array,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    out_dtype=None,
) -> jax.Array:
    """Reference paged attention: gather the table, dequantize, attend dense.

    Arithmetic-identical to the historical in-model path: ``pool[table]``
    gather, ``(codes.astype(f32) * scale).astype(out_dtype)`` dequant,
    block-structure flatten, then :func:`xla_attention` under the positional
    mask ``k_pos <= base + s``. This is the exactness reference the kernel's
    parity gates pin against, and the off-TPU arm of the dispatcher.
    """
    batch, heads, S, head_dim = q.shape
    block_size = k.shape[2]
    width = block_table.shape[1]
    capacity = width * block_size
    out_dtype = q.dtype if out_dtype is None else out_dtype

    def gather(pool_leaf, scale_leaf):
        blocks = pool_leaf[block_table]  # (batch, width, heads, bs, hd)
        if scale_leaf is not None:
            blocks = (blocks.astype(jnp.float32) * scale_leaf[block_table]).astype(out_dtype)
        return jnp.moveaxis(blocks, 2, 1).reshape(batch, heads, capacity, head_dim)

    k_pos = jnp.arange(capacity)
    q_pos = base_positions.astype(jnp.int32)[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    mask = (k_pos[None, None, :] <= q_pos[:, :, None])[:, None, :, :]
    return xla_attention(q, gather(k, k_scale), gather(v, v_scale), mask=mask)


def _paged_kernel(
    table_ref,  # scalar prefetch: (batch, width) int32
    base_ref,  # scalar prefetch: (batch,) int32 query base positions
    q_ref,  # (1, gh, S, hd)
    k_ref,  # (1, gh, bs, hd) one pool block's codes (int8/f32) or bf16 values
    v_ref,
    *rest,  # [k_scale_ref, v_scale_ref] when quantized, then o_ref + scratch
    block_size: int,
    sm_scale: float,
    quantized: bool,
    out_dtype,
):
    """One (batch row, head group, table column) program of the online softmax.

    The scalar-prefetched table row already steered this block's DMA (see the
    index maps in :func:`_paged_forward`); the body only needs the COLUMN index
    for positional masking: logical key position ``w * block_size + o`` against
    the row's query base. Scratch (acc, m, l) persists across the innermost
    grid axis — initialized at the first column, normalized into ``o_ref`` at
    the last — exactly the flash-attention recurrence of
    ``attention._flash_kernel``, walked over the table instead of a dense KV.

    Dequant mirrors the XLA gather arm bit for bit on VALUES:
    ``(codes.astype(f32) * scale).astype(out_dtype)`` — the cast to the compute
    dtype is the same value quantization ``gather_table`` applied, so both arms
    attend over identical K/V elements and differ only in summation order.
    """
    if quantized:
        k_scale_ref, v_scale_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        k_scale_ref = v_scale_ref = None
        o_ref, acc_ref, m_ref, l_ref = rest

    w = pl.program_id(2)
    nw = pl.num_programs(2)

    @pl.when(w == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    gh, S, head_dim = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
    q = q_ref[0].astype(jnp.float32)  # (gh, S, hd)
    k = k_ref[0]
    v = v_ref[0]
    if quantized:
        # per-(block, head) scalar scales, shaped (1, gh) by the block spec
        ks = k_scale_ref[0][:, None, None]
        vs = v_scale_ref[0][:, None, None]
        k = (k.astype(jnp.float32) * ks).astype(out_dtype)
        v = (v.astype(jnp.float32) * vs).astype(out_dtype)
    k = k.astype(jnp.float32)  # (gh, bs, hd)
    v = v.astype(jnp.float32)

    if gh == 1:
        scores = jax.lax.dot_general(
            q[0], k[0], (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )[None]  # (1, S, bs)
    else:
        scores = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
        )  # (gh, S, bs)
    scores = scores * sm_scale

    base = base_ref[pl.program_id(0)]
    k_pos = w * block_size + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 2)
    q_pos = base + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    valid = k_pos <= q_pos
    scores = jnp.where(valid, scores, _NEG_INF)

    m_prev = jnp.max(m_ref[...], axis=-1, keepdims=True)  # (gh, S, 1) lanes replicated
    l_prev = jnp.max(l_ref[...], axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1, keepdims=True))
    # a fully-masked block (scratch column / beyond the row) must contribute
    # exactly 0: for live rows exp underflows there anyway, but when EVERY
    # column is masked m_new stays _NEG_INF and exp(0) would be 1
    probs = jnp.where(valid, jnp.exp(scores - m_new), 0.0)
    correction = jnp.exp(m_prev - m_new)
    if gh == 1:
        pv = jax.lax.dot_general(
            probs[0], v[0], (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )[None]
    else:
        pv = jax.lax.dot_general(
            probs, v, (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
        )
    acc_ref[...] = acc_ref[...] * correction + pv
    l_new = l_prev * correction + jnp.sum(probs, axis=-1, keepdims=True)
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(w == nw - 1)
    def _finalize():
        l_final = jnp.max(l_ref[...], axis=-1, keepdims=True)
        o_ref[0] = (acc_ref[...] / jnp.maximum(l_final, 1e-30)).astype(o_ref.dtype)


def _paged_forward(
    q, k, v, block_table, base_positions, k_scale, v_scale, out_dtype,
    heads_per_step, interpret,
):
    batch, heads, S, head_dim = q.shape
    block_size = k.shape[2]
    width = block_table.shape[1]
    quantized = k_scale is not None
    gh = heads_per_step if heads % heads_per_step == 0 else 1
    sm_scale = 1.0 / np.sqrt(head_dim)

    kernel = functools.partial(
        _paged_kernel,
        block_size=block_size,
        sm_scale=sm_scale,
        quantized=quantized,
        out_dtype=out_dtype,
    )
    # index maps see (b, h, w, table_ref, base_ref): the scalar-prefetched table
    # row turns the grid's column coordinate into the pool block to DMA — this
    # indirection IS the kernel's reason to exist (no gathered copy)
    in_specs = [
        pl.BlockSpec((1, gh, S, head_dim), lambda b, h, w, tbl, base: (b, h, 0, 0)),
        pl.BlockSpec((1, gh, block_size, head_dim), lambda b, h, w, tbl, base: (tbl[b, w], h, 0, 0)),
        pl.BlockSpec((1, gh, block_size, head_dim), lambda b, h, w, tbl, base: (tbl[b, w], h, 0, 0)),
    ]
    operands = [q, k, v]
    if quantized:
        scale2 = lambda s: s.reshape(s.shape[0], heads)
        in_specs.append(pl.BlockSpec((1, gh), lambda b, h, w, tbl, base: (tbl[b, w], h)))
        in_specs.append(pl.BlockSpec((1, gh), lambda b, h, w, tbl, base: (tbl[b, w], h)))
        operands.extend([scale2(k_scale), scale2(v_scale)])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch, heads // gh, width),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, gh, S, head_dim), lambda b, h, w, tbl, base: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((gh, S, head_dim), jnp.float32),
            pltpu.VMEM((gh, S, 128), jnp.float32),
            pltpu.VMEM((gh, S, 128), jnp.float32),
        ],
    )
    codes_bytes = 2 * width * heads * block_size * head_dim * k.dtype.itemsize
    scale_bytes = 2 * width * heads * 4 if quantized else 0
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((batch, heads, S, head_dim), out_dtype),
        cost_estimate=pl.CostEstimate(
            flops=4 * batch * heads * S * width * block_size * head_dim,
            bytes_accessed=batch * (q.size // batch * 2 * q.dtype.itemsize + codes_bytes + scale_bytes),
            transcendentals=batch * heads * S * width * block_size,
        ),
        interpret=interpret,
    )(
        block_table.astype(jnp.int32),
        jnp.asarray(base_positions, jnp.int32).reshape(batch),
        *operands,
    )
    return out


def resolve_paged_impl(
    impl: str, table_width: int, block_size: int, heads: int, head_dim: int
) -> str:
    """Resolve ``"auto"`` to the backend the dispatcher would pick.

    Exposed separately so serving telemetry (``unionml_paged_attn_impl``, the
    ``/stats`` ``impl`` field) can report the selection without tracing."""
    if impl == "auto":
        if on_tpu():
            from unionml_tpu.ops.tuning import pick_paged_impl

            return pick_paged_impl(table_width, block_size, heads, head_dim)
        return "xla"
    if impl in ("pallas", "xla"):
        return impl
    raise ValueError(f"Unknown paged attention impl {impl!r}; expected 'auto', 'pallas', or 'xla'")


def paged_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_table: jax.Array,
    base_positions: jax.Array,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    out_dtype=None,
    impl: str = "auto",
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Attend ``q`` over a row's paged KV through its block-table row.

    :param q: ``(batch, heads, S, head_dim)`` queries (``S == 1`` decode; the
        batch-1 chunk-prefill path passes the whole chunk).
    :param k / v: pool leaves ``(num_blocks, heads, block_size, head_dim)`` —
        int8 codes when ``k_scale``/``v_scale`` ride along, else the compute
        dtype. (The speculative-verify path passes its gathered local state
        reshaped to this layout with an identity table; codes may then be f32
        holding exact integers — the dequant arithmetic is dtype-agnostic.)
    :param block_table: ``(batch, width)`` int32 map from logical block index
        to pool block; unmapped tail columns point at the scratch block.
    :param base_positions: ``(batch,)`` int32; query token ``s`` of row ``b``
        sits at logical position ``base_positions[b] + s`` and attends key
        positions ``<= base + s``. Retired rows carry the sentinel position —
        their masked output is garbage the engine never samples.
    :param k_scale / v_scale: ``(num_blocks, heads, 1, 1)`` f32 monotone block
        scales (int8 pools); ``None`` selects the full-precision variant.
    :param out_dtype: dequant target (the compute dtype); defaults to
        ``q.dtype``. Matches the XLA arm's value quantization exactly.
    :param impl: ``"auto"`` (pallas on TPU, XLA elsewhere — measured verdicts
        override per shape class), ``"pallas"``, or ``"xla"``.
    :param interpret: force pallas interpret mode; ``None`` auto-selects it off
        TPU, so CPU tests can pin ``impl="pallas"`` with no extra plumbing.
    """
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be passed together")
    out_dtype = q.dtype if out_dtype is None else out_dtype
    batch, heads, _, head_dim = q.shape
    block_size = k.shape[2]
    width = block_table.shape[1]
    impl = resolve_paged_impl(impl, width, block_size, heads, head_dim)
    if impl == "xla":
        return xla_paged_attention(
            q, k, v, block_table, base_positions,
            k_scale=k_scale, v_scale=v_scale, out_dtype=out_dtype,
        )
    if interpret is None:
        interpret = not on_tpu()
    from unionml_tpu.ops.tuning import pick_paged_heads

    heads_per_step = pick_paged_heads(width, block_size, heads, head_dim)
    return _paged_forward(
        q, k, v, block_table, base_positions, k_scale, v_scale, out_dtype,
        heads_per_step, interpret,
    )


def fused_hbm_bytes(
    table_width: int, block_size: int, heads: int, head_dim: int,
    quantized: bool, dense_itemsize: int = 2,
) -> int:
    """Modeled HBM bytes one decode step's KV reads cost the FUSED kernel.

    K + V codes at their stored width (int8 under quantization, else the dense
    dtype) plus the f32 scales — nothing else touches HBM for KV: the kernel
    dequantizes in VMEM and never materializes a gathered copy. This is the
    traffic model ``bench_kernels.py --paged`` gates on (exits nonzero if the
    kernel's modeled bytes exceed exactly this sum).
    """
    kv_positions = 2 * table_width * block_size * heads * head_dim
    codes = kv_positions * (1 if quantized else dense_itemsize)
    scales = 2 * table_width * heads * 4 if quantized else 0
    return codes + scales


def gather_hbm_bytes(
    table_width: int, block_size: int, heads: int, head_dim: int,
    quantized: bool, dense_itemsize: int = 2,
) -> int:
    """Modeled HBM bytes of the XLA gather arm for the same step.

    The gather reads the stored pool (codes + scales), then WRITES the dense
    dequantized copy and READS it back into the attention — the round trip the
    fused kernel deletes. (XLA may fuse part of this on some shapes; the model
    prices the materialization its HLO schedules on the measured serving path.)
    """
    kv_positions = 2 * table_width * block_size * heads * head_dim
    dense_copy = 2 * kv_positions * dense_itemsize  # write + read back
    return fused_hbm_bytes(
        table_width, block_size, heads, head_dim, quantized, dense_itemsize
    ) + dense_copy
