"""Attention ops: pallas flash-attention TPU kernel with an XLA fallback.

The reference delegates all math to user frameworks (SURVEY.md §2: "no CUDA/C++
anywhere"); in the TPU rebuild the attention hot op is owned by the framework. Two
implementations behind one dispatcher:

- ``impl="pallas"``: blocked flash attention (online softmax) keeping the working set
  in VMEM, f32 accumulation on the MXU, O(seq) memory. Grid: (batch*heads, q_blocks);
  the KV scan runs inside the kernel with ``jax.lax.fori_loop``. The BACKWARD is also
  pallas: the forward saves per-row logsumexp residuals and the dq / dk+dv kernels
  recompute probabilities blockwise (flash-attention-2 style), so training never
  materializes the (seq x seq) score matrix either.
- ``impl="xla"``: the standard fused-by-XLA softmax(QK^T)V — the exact reference, the
  dense-mask path, and the fallback for non-tile-aligned shapes (fwd and bwd).
- ``impl="auto"``: pallas on TPU backends, XLA elsewhere (CPU tests run the fallback).

Shapes follow the (batch, num_heads, seq, head_dim) convention.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def segment_mask(segment_ids: jax.Array) -> jax.Array:
    """(batch, seq) packed segment ids -> (batch, 1, seq, seq) attention mask.

    Convention (t5x/flax): ``0`` marks padding, positive ints mark segments; a
    query attends a key iff they carry the same positive id. This dense mask is
    what packing costs on the XLA path — O(seq^2) HBM per row — and what the
    pallas kernel's blockwise comparison avoids.
    """
    same = segment_ids[:, :, None] == segment_ids[:, None, :]
    valid = same & (segment_ids > 0)[:, None, :] & (segment_ids > 0)[:, :, None]
    return valid[:, None, :, :]


def xla_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Reference attention; XLA fuses the softmax chain. Used as fallback + backward."""
    *_, seq_q, head_dim = q.shape
    seq_k = k.shape[-2]
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(head_dim)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        causal_mask = jnp.tril(jnp.ones((seq_q, seq_k), dtype=bool))
        logits = jnp.where(causal_mask[None, None], logits, _NEG_INF)
    if segment_ids is not None:
        # sliced per axis so cross-length (seq_q != seq_k) calls mask correctly,
        # matching the pallas path's _segment_arrays slicing
        ids_q = segment_ids[:, :seq_q]
        ids_k = segment_ids[:, :seq_k]
        valid = (
            (ids_q[:, :, None] == ids_k[:, None, :])
            & (ids_q > 0)[:, :, None]
            & (ids_k > 0)[:, None, :]
        )
        logits = jnp.where(valid[:, None], logits, _NEG_INF)
    if mask is not None:
        logits = jnp.where(mask, logits, _NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    # fully-masked rows (padding in a packed batch) softmax to uniform garbage;
    # zero them so packed outputs match the per-sequence reference exactly
    if segment_ids is not None:
        weights = jnp.where((ids_q > 0)[:, None, :, None], weights, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v.dtype), v)


def _flash_kernel(
    kv_len_ref,
    q_ref,
    k_ref,
    v_ref,
    *rest,
    block_k: int,
    seq_k: int,
    causal: bool,
    sm_scale: float,
    block_q: int,
    packed: bool = False,
    heads: int = 1,
):
    """One (batch*head, q_block) program: stream KV blocks with an online softmax.

    ``kv_len_ref`` is the whole (batch*heads,) valid-KV-length vector in SMEM
    (Mosaic only allows rank-1 blocks that are whole-array or lane-tile multiples,
    so it is passed unblocked and indexed by the grid's batch*head coordinate);
    K positions >= kv_len contribute nothing. When pallas passes a second output
    ref (``lse_ref``), the per-row logsumexp is written as the backward residual.

    ``packed`` prepends four extra input refs: packed segment ids in
    Mosaic-friendly layouts — (1, block_q, 1) and (1, 1, seq_k) blocks of the
    (batch, seq, 1) / (batch, 1, seq) id arrays — adding the blockwise
    same-segment constraint that packing needs WITHOUT a dense (seq, seq) mask,
    plus the rank-1 SMEM block-skip bounds from :func:`_segment_block_bounds`
    (this q block's live KV range), so cross-segment KV blocks are never even
    loaded — per-row work is O(sum seg_len^2), not O(seq^2).
    """
    if packed:
        seg_q_ref, seg_k_ref, kvb_start_ref, kvb_stop_ref, o_ref, *maybe_lse = rest
    else:
        seg_q_ref = seg_k_ref = kvb_start_ref = kvb_stop_ref = None
        o_ref, *maybe_lse = rest
    lse_ref = maybe_lse[0] if maybe_lse else None

    q = q_ref[0].astype(jnp.float32) * sm_scale  # (block_q, head_dim)
    q_index = pl.program_id(1)
    kv_len = kv_len_ref[pl.program_id(0)]
    seg_q = None if seg_q_ref is None else seg_q_ref[0].reshape(block_q, 1)

    acc = jnp.zeros((block_q, q.shape[-1]), dtype=jnp.float32)
    row_max = jnp.full((block_q, 1), _NEG_INF, dtype=jnp.float32)
    row_sum = jnp.zeros((block_q, 1), dtype=jnp.float32)

    num_k_blocks = seq_k // block_k

    def body(k_idx, carry):
        acc, row_max, row_sum = carry
        k_block = k_ref[0, pl.ds(k_idx * block_k, block_k), :].astype(jnp.float32)
        v_block = v_ref[0, pl.ds(k_idx * block_k, block_k), :].astype(jnp.float32)

        scores = jax.lax.dot_general(
            q, k_block, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)

        k_pos = k_idx * block_k + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        valid = k_pos < kv_len
        if seg_q is not None:
            seg_k = seg_k_ref[0, :, pl.ds(k_idx * block_k, block_k)]  # (1, block_k)
            valid = jnp.logical_and(valid, jnp.logical_and(seg_q == seg_k, seg_q > 0))
        if causal:
            q_pos = q_index * block_q + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0)
            valid = jnp.logical_and(valid, q_pos >= k_pos)
        scores = jnp.where(valid, scores, _NEG_INF)

        new_max = jnp.maximum(row_max, jnp.max(scores, axis=-1, keepdims=True))
        correction = jnp.exp(row_max - new_max)
        # masked slots must contribute exactly 0: for a live row exp(scores - new_max)
        # already underflows to 0 there, but for a FULLY-masked row (packed padding)
        # new_max == scores == _NEG_INF and exp(0) would be 1 — the where() is what
        # keeps row_sum at 0 so such rows divide to zeros below
        probs = jnp.where(valid, jnp.exp(scores - new_max), 0.0)
        acc = acc * correction + jax.lax.dot_general(
            probs, v_block, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        row_sum = row_sum * correction + jnp.sum(probs, axis=-1, keepdims=True)
        return acc, new_max, row_sum

    # bound the scan: skip fully-masked KV blocks (padding tail; causal upper
    # triangle; packed: everything outside this q block's own segments)
    first_block = jnp.int32(0)
    last_block = jnp.minimum(num_k_blocks, pl.cdiv(kv_len, block_k))
    if causal:
        last_block = jnp.minimum(last_block, pl.cdiv((q_index + 1) * block_q, block_k))
    if packed:
        num_q_blocks = pl.num_programs(1)
        bounds_row = (pl.program_id(0) // heads) * num_q_blocks + q_index
        first_block = jnp.maximum(first_block, kvb_start_ref[bounds_row])
        last_block = jnp.minimum(last_block, kvb_stop_ref[bounds_row])
    acc, row_max, row_sum = jax.lax.fori_loop(
        first_block, last_block, body, (acc, row_max, row_sum)
    )
    # fully-masked rows (packed padding) carry acc == row_sum == 0 — the masked probs
    # above guarantee it — so the guarded divide emits the zeros the XLA reference
    # and the ring kernel produce for such rows
    o_ref[0] = (acc / jnp.maximum(row_sum, 1e-30)).astype(o_ref.dtype)
    if lse_ref is not None:
        # logsumexp of the (scaled, masked) scores — the residual the backward needs
        lse = row_max + jnp.log(jnp.maximum(row_sum, 1e-30))
        lse_ref[0] = lse.reshape(lse_ref.shape[1:]).astype(jnp.float32)


def _tile_aligned(seq_q: int, seq_k: int, head_dim: int, block_q: int, block_k: int) -> bool:
    # irregular shapes fall back to XLA for exactness; head_dim down to 64 is allowed
    # (mosaic pads the lane dim), smaller/odd head dims are not worth the kernel
    return not (seq_q % block_q or seq_k % block_k or head_dim % 64)


def _segment_arrays(segment_ids: jax.Array, seq_q: int, seq_k: int):
    """Packed ids -> the kernels' Mosaic-friendly operands.

    Returns ``(seg_q3, seg_k3, kv_lens)``: (batch, seq_q, 1) and (batch, 1, seq_k)
    int32 views (the trailing/leading singleton keeps blocks on the proven
    (block, 1)/(1, block) tilings) plus the per-row valid length. kv_len is the
    last-nonzero index + 1 (not the nonzero COUNT): pack_sequences emits padding
    as a contiguous zero suffix where the two agree, but hand-built ids with
    interior zeros must degrade to in-block masking — counting would silently
    skip trailing live blocks.
    """
    ids = segment_ids.astype(jnp.int32)
    seg_q3 = ids[:, :seq_q, None]
    seg_k3 = ids[:, None, :seq_k]
    positions = jnp.arange(seq_k, dtype=jnp.int32)[None, :]
    kv_lens = jnp.max(jnp.where(ids[:, :seq_k] > 0, positions + 1, 0), axis=-1)
    return seg_q3, seg_k3, kv_lens


def _segment_block_bounds(segment_ids, block: int, other_block: int):
    """Per-chunk live range of the other axis — the packed kernels' block-skip map.

    ``segment_ids`` is a ``(block_axis_ids, other_axis_ids)`` pair — e.g. the
    q-side slice and the kv-side slice of the packed id array; lengths may
    differ (cross-length attention slices both from one array). A chunk of
    ``block`` positions on the block axis may only interact with other-axis
    positions of the segments it contains (plus nothing, for pure padding). For
    each row and chunk this computes the union of its segments' TRUE other-axis
    extents — scatter-min/max over segment IDS, not run boundaries, so rows
    that reuse an id non-contiguously get the full (conservative) extent and
    stay exact — and returns ``(start, stop)`` int32 arrays of shape
    (batch, s_block // block), in units of ``other_block``,
    flattened-rank-1-ready for SMEM. Empty chunks (and ids absent from the
    other axis) get start >= stop (the fori_loop runs zero iterations).
    Out-of-range ids clamp into one shared bucket: merged extents are
    supersets, and in-block masking keeps supersets exact.

    This is where packing pays on TPU: total kernel work drops from
    O(seq^2) to O(sum_i seg_len_i^2) per row — the XLA path cannot skip, it
    materializes the dense mask and computes every pair.
    """
    block_ids, other_ids = (x.astype(jnp.int32) for x in segment_ids)
    batch, s_other = other_ids.shape
    s_block = block_ids.shape[1]
    cap = max(s_block, s_other)  # shared clip bucket for out-of-range ids
    pos_o = jnp.broadcast_to(jnp.arange(s_other, dtype=jnp.int32)[None, :], other_ids.shape)
    rows_o = jnp.broadcast_to(jnp.arange(batch, dtype=jnp.int32)[:, None], other_ids.shape)
    safe_o = jnp.clip(other_ids, 0, cap)
    first_of_id = jnp.full((batch, cap + 1), s_other, jnp.int32).at[rows_o, safe_o].min(pos_o)
    end_of_id = jnp.zeros((batch, cap + 1), jnp.int32).at[rows_o, safe_o].max(pos_o + 1)
    safe_b = jnp.clip(block_ids, 0, cap)
    seg_start = jnp.take_along_axis(first_of_id, safe_b, axis=1)  # (batch, s_block)
    seg_end = jnp.take_along_axis(end_of_id, safe_b, axis=1)
    live = block_ids > 0
    n_chunks = s_block // block
    chunk_start = jnp.min(
        jnp.where(live, seg_start, s_other).reshape(batch, n_chunks, block), axis=2
    )
    chunk_end = jnp.max(jnp.where(live, seg_end, 0).reshape(batch, n_chunks, block), axis=2)
    start_blocks = chunk_start // other_block
    stop_blocks = -(-chunk_end // other_block)  # cdiv
    return start_blocks.reshape(-1), stop_blocks.reshape(-1)


def _flash_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_lens: Optional[jax.Array],
    causal: bool,
    sm_scale: float,
    block_q: int,
    block_k: int,
    interpret: bool,
    return_residuals: bool = False,
    segment_ids: Optional[jax.Array] = None,
):
    batch, heads, seq_q, head_dim = q.shape
    seq_k = k.shape[-2]

    if not _tile_aligned(seq_q, seq_k, head_dim, block_q, block_k):
        mask = _kv_lens_to_mask(kv_lens, seq_k) if kv_lens is not None else None
        out = xla_attention(
            q, k, v, mask=mask, causal=causal, sm_scale=sm_scale, segment_ids=segment_ids
        )
        return (out, None) if return_residuals else out

    bh = batch * heads
    q3 = q.reshape(bh, seq_q, head_dim)
    k3 = k.reshape(bh, seq_k, head_dim)
    v3 = v.reshape(bh, seq_k, head_dim)
    packed = segment_ids is not None
    if packed:
        seg_q3, seg_k3, kv_lens = _segment_arrays(segment_ids, seq_q, seq_k)
    if kv_lens is None:
        kv_lens = jnp.full((batch,), seq_k, dtype=jnp.int32)
    kv_lens_bh = jnp.repeat(kv_lens.astype(jnp.int32), heads)

    kernel = functools.partial(
        _flash_kernel,
        block_k=block_k,
        seq_k=seq_k,
        causal=causal,
        sm_scale=sm_scale,
        block_q=block_q,
        packed=packed,
        heads=heads,
    )
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # whole kv_lens vector, unblocked
        pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, seq_k, head_dim), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((1, seq_k, head_dim), lambda b, i: (b, 0, 0)),
    ]
    operands = [kv_lens_bh, q3, k3, v3]
    if packed:
        # segment ids are per-batch-row; the index map folds the head axis away
        in_specs.append(pl.BlockSpec((1, block_q, 1), lambda b, i: (b // heads, i, 0)))
        in_specs.append(pl.BlockSpec((1, 1, seq_k), lambda b, i: (b // heads, 0, 0)))
        operands.extend([seg_q3, seg_k3])
        # per-q-block live KV ranges: rank-1 SMEM, row = batch * n_q_blocks + i
        ids32 = segment_ids.astype(jnp.int32)
        kvb_start, kvb_stop = _segment_block_bounds(
            (ids32[:, :seq_q], ids32[:, :seq_k]), block_q, block_k
        )
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.extend([kvb_start, kvb_stop])
    out_shape = [jax.ShapeDtypeStruct((bh, seq_q, head_dim), q.dtype)]
    out_specs = [pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0))]
    if return_residuals:
        # trailing singleton keeps the block's last-two dims Mosaic-tileable:
        # (block_q, 1) has last dim == array dim and block_q % 8 == 0
        out_shape.append(jax.ShapeDtypeStruct((bh, seq_q, 1), jnp.float32))
        out_specs.append(pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)))
    result = pl.pallas_call(
        kernel,
        grid=(bh, seq_q // block_q),
        in_specs=in_specs,
        out_specs=out_specs if return_residuals else out_specs[0],
        out_shape=out_shape if return_residuals else out_shape[0],
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * seq_q * seq_k * head_dim,
            bytes_accessed=(q3.size + k3.size + v3.size + q3.size) * q3.dtype.itemsize,
            transcendentals=bh * seq_q * seq_k,
        ),
        interpret=interpret,
    )(*operands)
    if return_residuals:
        out, lse = result
        return out.reshape(batch, heads, seq_q, head_dim), lse.reshape(batch, heads, seq_q)
    return result.reshape(batch, heads, seq_q, head_dim)


def _kv_lens_to_mask(kv_lens: jax.Array, seq_k: int) -> jax.Array:
    """(batch,) valid lengths -> (batch, 1, 1, seq_k) boolean padding mask."""
    positions = jnp.arange(seq_k)[None, :]
    return (positions < kv_lens[:, None])[:, None, None, :]


def _bwd_dq_kernel(
    kv_len_ref,
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    *rest,
    block_k: int,
    seq_k: int,
    causal: bool,
    sm_scale: float,
    block_q: int,
    packed: bool = False,
    heads: int = 1,
):
    """dQ for one (batch*head, q_block): stream KV blocks, recompute probabilities."""
    if packed:
        seg_q_ref, seg_k_ref, kvb_start_ref, kvb_stop_ref, dq_ref = rest
    else:
        seg_q_ref = seg_k_ref = kvb_start_ref = kvb_stop_ref = None
        (dq_ref,) = rest
    qs = q_ref[0].astype(jnp.float32) * sm_scale  # (block_q, d); scores are pre-scaled
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0].reshape(block_q, 1)
    delta = delta_ref[0].reshape(block_q, 1)
    q_index = pl.program_id(1)
    kv_len = kv_len_ref[pl.program_id(0)]
    seg_q = None if seg_q_ref is None else seg_q_ref[0].reshape(block_q, 1)

    dq = jnp.zeros((block_q, qs.shape[-1]), dtype=jnp.float32)
    num_k_blocks = seq_k // block_k

    def body(k_idx, dq):
        k_block = k_ref[0, pl.ds(k_idx * block_k, block_k), :].astype(jnp.float32)
        v_block = v_ref[0, pl.ds(k_idx * block_k, block_k), :].astype(jnp.float32)
        scores = jax.lax.dot_general(
            qs, k_block, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        k_pos = k_idx * block_k + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        valid = k_pos < kv_len
        if seg_q is not None:
            seg_k = seg_k_ref[0, :, pl.ds(k_idx * block_k, block_k)]  # (1, block_k)
            valid = jnp.logical_and(valid, jnp.logical_and(seg_q == seg_k, seg_q > 0))
        if causal:
            q_pos = q_index * block_q + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0)
            valid = jnp.logical_and(valid, q_pos >= k_pos)
        probs = jnp.where(valid, jnp.exp(scores - lse), 0.0)
        dp = jax.lax.dot_general(do, v_block, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        dscores = probs * (dp - delta)
        return dq + jax.lax.dot_general(
            dscores, k_block, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    first_block = jnp.int32(0)
    last_block = jnp.minimum(num_k_blocks, pl.cdiv(kv_len, block_k))
    if causal:
        last_block = jnp.minimum(last_block, pl.cdiv((q_index + 1) * block_q, block_k))
    if packed:
        # same per-q-block live KV range the forward used (see _segment_block_bounds)
        bounds_row = (pl.program_id(0) // heads) * pl.num_programs(1) + q_index
        first_block = jnp.maximum(first_block, kvb_start_ref[bounds_row])
        last_block = jnp.minimum(last_block, kvb_stop_ref[bounds_row])
    dq = jax.lax.fori_loop(first_block, last_block, body, dq)
    dq_ref[0] = (dq * sm_scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    kv_len_ref,
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    *rest,
    block_q: int,
    seq_q: int,
    causal: bool,
    sm_scale: float,
    block_k: int,
    packed: bool = False,
    heads: int = 1,
):
    """dK/dV for one (batch*head, kv_block): stream Q blocks, recompute probabilities."""
    if packed:
        seg_q_ref, seg_k_ref, qb_start_ref, qb_stop_ref, dk_ref, dv_ref = rest
    else:
        seg_q_ref = seg_k_ref = qb_start_ref = qb_stop_ref = None
        dk_ref, dv_ref = rest
    k_block = k_ref[0].astype(jnp.float32)  # (block_k, d)
    v_block = v_ref[0].astype(jnp.float32)
    kv_index = pl.program_id(1)
    kv_len = kv_len_ref[pl.program_id(0)]
    # this program's fixed (1, block_k) key-segment row
    seg_k = None if seg_k_ref is None else seg_k_ref[0]

    dk = jnp.zeros_like(k_block)
    dv = jnp.zeros_like(v_block)
    num_q_blocks = seq_q // block_q

    def body(q_idx, carry):
        dk, dv = carry
        qs = q_ref[0, pl.ds(q_idx * block_q, block_q), :].astype(jnp.float32) * sm_scale
        do = do_ref[0, pl.ds(q_idx * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(q_idx * block_q, block_q)].reshape(block_q, 1)
        delta = delta_ref[0, pl.ds(q_idx * block_q, block_q)].reshape(block_q, 1)

        scores = jax.lax.dot_general(
            qs, k_block, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)
        k_pos = kv_index * block_k + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        valid = k_pos < kv_len
        if seg_k is not None:
            seg_q = seg_q_ref[0, pl.ds(q_idx * block_q, block_q), :]  # (block_q, 1)
            valid = jnp.logical_and(valid, jnp.logical_and(seg_q == seg_k, seg_q > 0))
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0)
            valid = jnp.logical_and(valid, q_pos >= k_pos)
        probs = jnp.where(valid, jnp.exp(scores - lse), 0.0)

        dv = dv + jax.lax.dot_general(
            probs, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(do, v_block, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        dscores = probs * (dp - delta)
        # qs already carries sm_scale, so this is the gradient wrt the original K
        dk = dk + jax.lax.dot_general(
            dscores, qs, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk, dv

    # causal: q blocks strictly above this kv block's diagonal contribute nothing;
    # kv blocks entirely beyond kv_len (padding tail) skip the whole scan; packed
    # rows additionally scan only the q blocks whose segments touch this kv block
    # (transposed _segment_block_bounds map — same O(sum seg_len^2) economics as
    # the forward)
    first_block = (kv_index * block_k) // block_q if causal else jnp.int32(0)
    in_range = kv_index * block_k < kv_len
    num_live_q_blocks = num_q_blocks
    if packed:
        # the transposed _segment_block_bounds map is the exact live-q-block
        # bound; a kv_len-derived bound would measure KV length in Q-block
        # units and drop dk/dv rows whenever seq_q > seq_k (ADVICE round 4)
        bounds_row = (pl.program_id(0) // heads) * pl.num_programs(1) + kv_index
        first_block = jnp.maximum(first_block, qb_start_ref[bounds_row])
        num_live_q_blocks = jnp.minimum(num_live_q_blocks, qb_stop_ref[bounds_row])
    last_block = jnp.where(in_range, num_live_q_blocks, first_block)
    dk, dv = jax.lax.fori_loop(first_block, last_block, body, (dk, dv))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_backward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_lens: Optional[jax.Array],
    out: jax.Array,
    lse: jax.Array,
    g: jax.Array,
    causal: bool,
    sm_scale: float,
    block_q: int,
    block_k: int,
    interpret: bool,
    segment_ids: Optional[jax.Array] = None,
):
    """Pallas flash backward: dq/dk/dv with O(seq) memory, probabilities recomputed."""
    batch, heads, seq_q, head_dim = q.shape
    seq_k = k.shape[-2]
    bh = batch * heads

    reshape3 = lambda x: x.reshape(bh, x.shape[-2], x.shape[-1])
    q3, k3, v3, do3 = reshape3(q), reshape3(k), reshape3(v), reshape3(g)
    # trailing singleton: see the forward's residual out_spec comment
    lse3 = lse.reshape(bh, seq_q, 1)
    # delta_i = rowsum(dO * O): the softmax-jacobian correction term
    delta3 = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1).reshape(bh, seq_q, 1)
    packed = segment_ids is not None
    if packed:
        seg_q3, seg_k3, kv_lens = _segment_arrays(segment_ids, seq_q, seq_k)
    if kv_lens is None:
        kv_lens_bh = jnp.full((bh,), seq_k, dtype=jnp.int32)
    else:
        kv_lens_bh = jnp.repeat(kv_lens.astype(jnp.int32), heads)

    seg_operands = [seg_q3, seg_k3] if packed else []
    seg_specs = (
        [
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b // heads, i, 0)),
            pl.BlockSpec((1, 1, seq_k), lambda b, i: (b // heads, 0, 0)),
        ]
        if packed
        else []
    )

    if packed:
        ids32 = segment_ids.astype(jnp.int32)
        kvb_start, kvb_stop = _segment_block_bounds(
            (ids32[:, :seq_q], ids32[:, :seq_k]), block_q, block_k
        )
        qb_start, qb_stop = _segment_block_bounds(
            (ids32[:, :seq_k], ids32[:, :seq_q]), block_k, block_q
        )
        dq_seg_operands = [*seg_operands, kvb_start, kvb_stop]
        dq_seg_specs = seg_specs + [
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ]
    else:
        dq_seg_operands = seg_operands
        dq_seg_specs = seg_specs

    dq_kernel = functools.partial(
        _bwd_dq_kernel,
        block_k=block_k,
        seq_k=seq_k,
        causal=causal,
        sm_scale=sm_scale,
        block_q=block_q,
        packed=packed,
        heads=heads,
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, seq_q // block_q),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # whole kv_lens vector, unblocked
            pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_k, head_dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_k, head_dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ]
        + dq_seg_specs,
        out_specs=pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq_q, head_dim), q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=6 * bh * seq_q * seq_k * head_dim,  # scores + dp + dq matmuls
            bytes_accessed=(q3.size + k3.size + v3.size + 2 * do3.size) * q3.dtype.itemsize,
            transcendentals=bh * seq_q * seq_k,
        ),
        interpret=interpret,
    )(kv_lens_bh, q3, k3, v3, do3, lse3, delta3, *dq_seg_operands)

    # the dkv grid iterates kv blocks: the key-segment operand is blocked, the
    # query-segment row streams whole
    dkv_seg_specs = (
        [
            pl.BlockSpec((1, seq_q, 1), lambda b, j: (b // heads, 0, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b, j: (b // heads, 0, j)),
            pl.BlockSpec(memory_space=pltpu.SMEM),  # per-kv-block live q range
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ]
        if packed
        else []
    )
    dkv_seg_operands = [*seg_operands, qb_start, qb_stop] if packed else seg_operands
    dkv_kernel = functools.partial(
        _bwd_dkv_kernel,
        block_q=block_q,
        seq_q=seq_q,
        causal=causal,
        sm_scale=sm_scale,
        block_k=block_k,
        packed=packed,
        heads=heads,
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, seq_k // block_k),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # whole kv_lens vector, unblocked

            pl.BlockSpec((1, seq_q, head_dim), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, head_dim), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, head_dim), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, seq_q, head_dim), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, seq_q, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, seq_q, 1), lambda b, j: (b, 0, 0)),
        ]
        + dkv_seg_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, head_dim), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, head_dim), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_k, head_dim), k.dtype),
            jax.ShapeDtypeStruct((bh, seq_k, head_dim), v.dtype),
        ],
        cost_estimate=pl.CostEstimate(
            flops=8 * bh * seq_q * seq_k * head_dim,  # scores + dv + dp + dk matmuls
            bytes_accessed=(2 * q3.size + k3.size + v3.size + 2 * do3.size) * q3.dtype.itemsize,
            transcendentals=bh * seq_q * seq_k,
        ),
        interpret=interpret,
    )(kv_lens_bh, q3, k3, v3, do3, lse3, delta3, *dkv_seg_operands)

    unshape = lambda x, s: x.reshape(batch, heads, s, head_dim)
    return unshape(dq, seq_q), unshape(dk, seq_k), unshape(dv, seq_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_lens: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: bool = False,
) -> jax.Array:
    """Blocked flash attention (pallas), fully differentiable.

    Backward also runs pallas kernels (probabilities recomputed from the saved
    logsumexp residual — O(seq) memory both ways); irregular shapes fall back to the
    XLA path in both directions.

    :param kv_lens: optional (batch,) int32 valid KV lengths — the padding-mask case
        (keys at positions >= kv_lens[b] are masked for every head/query of batch b).
    :param segment_ids: optional (batch, seq) int32 packed segment ids (0 =
        padding, positive = segment; t5x convention): queries attend only keys of
        their own segment, blockwise in-kernel — the packed-training regime where
        the XLA path would need a dense (seq, seq) mask per row. Mutually exclusive
        with ``kv_lens`` (padding is already encoded as id 0).
    :param block_q / block_k: Mosaic tile edges; ``None`` resolves through
        :func:`unionml_tpu.ops.tuning.pick_block_sizes` (measured winners when a
        ``bench_kernels.py`` sweep has recorded them, aligned defaults otherwise).
    """
    if segment_ids is not None and kv_lens is not None:
        raise ValueError("segment_ids already encodes padding; pass kv_lens=None")
    block_q, block_k = _resolve_blocks(q, k, block_q, block_k, packed=segment_ids is not None)
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    return _flash_forward(
        q, k, v, kv_lens, causal, scale, block_q, block_k, interpret, segment_ids=segment_ids
    )


def _resolve_blocks(q, k, block_q, block_k, packed=False):
    if block_q is None or block_k is None:
        from unionml_tpu.ops.tuning import pick_block_sizes

        tuned_q, tuned_k = pick_block_sizes(
            q.shape[-2], k.shape[-2], q.shape[-1], packed=packed
        )
        block_q = block_q if block_q is not None else tuned_q
        block_k = block_k if block_k is not None else tuned_k
    return block_q, block_k


def _flash_fwd(q, k, v, kv_lens, segment_ids, causal, sm_scale, block_q, block_k, interpret):
    if segment_ids is not None and kv_lens is not None:
        raise ValueError("segment_ids already encodes padding; pass kv_lens=None")
    block_q, block_k = _resolve_blocks(q, k, block_q, block_k, packed=segment_ids is not None)
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    out, lse = _flash_forward(
        q,
        k,
        v,
        kv_lens,
        causal,
        scale,
        block_q,
        block_k,
        interpret,
        return_residuals=True,
        segment_ids=segment_ids,
    )
    # the XLA-fallback backward recomputes from q/k/v: don't keep `out` alive for it
    residual_out = out if lse is not None else None
    return out, (q, k, v, kv_lens, segment_ids, residual_out, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, residuals, g):
    q, k, v, kv_lens, segment_ids, out, lse = residuals
    block_q, block_k = _resolve_blocks(q, k, block_q, block_k, packed=segment_ids is not None)
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    if lse is not None:
        dq, dk, dv = _flash_backward(
            q, k, v, kv_lens, out, lse, g, causal, scale, block_q, block_k, interpret,
            segment_ids=segment_ids,
        )
        return dq, dk, dv, None, None
    # irregular-shape path: differentiate the XLA reference instead
    mask = _kv_lens_to_mask(kv_lens, k.shape[-2]) if kv_lens is not None else None
    _, vjp = jax.vjp(
        lambda q_, k_, v_: xla_attention(
            q_, k_, v_, mask=mask, causal=causal, sm_scale=scale, segment_ids=segment_ids
        ),
        q,
        k,
        v,
    )
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None, None


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def on_tpu() -> bool:
    """True only for genuine TPU devices (incl. remote-TPU plugin backends)."""
    if jax.default_backend() == "tpu":
        return True
    try:
        return "tpu" in jax.devices()[0].device_kind.lower()
    except Exception:  # graftlint: disable=swallowed-exception -- backend without device_kind: "not a TPU" is the correct total answer
        return False


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    kv_lens: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    impl: str = "auto",
) -> jax.Array:
    """Dispatching attention entrypoint used by the model zoo.

    ``impl="auto"`` consults the measured per-shape verdicts
    (:data:`unionml_tpu.ops.tuning.MEASURED_IMPL` — on v5e, XLA's fused attention
    wins or ties the pallas kernel at every measured practical shape, confirmed
    end-to-end by a 24% faster BERT-base train step; TPU_PROBES.log 2026-07-29).
    Dense ``mask`` arrays and non-TPU backends always take the XLA path;
    ``impl="pallas"`` forces the flash kernel with its tuned block sizes.

    ``segment_ids`` selects the packed-sequence regime: on TPU the verdict comes
    from :data:`unionml_tpu.ops.tuning.MEASURED_PACKED_IMPL` — here the pallas
    kernel's blockwise segment comparison avoids the dense O(seq^2) mask the XLA
    path must materialize per row.
    """
    if segment_ids is not None and kv_lens is not None:
        # enforced here (not only in flash_attention) so the XLA path rejects the
        # combination identically instead of silently combining both masks
        raise ValueError("segment_ids already encodes padding; pass kv_lens=None")
    if impl == "auto":
        if on_tpu() and mask is None:
            from unionml_tpu.ops.tuning import pick_impl, pick_packed_impl

            if segment_ids is not None:
                impl = pick_packed_impl(q.shape[-2], k.shape[-2], q.shape[-1])
            else:
                impl = pick_impl(q.shape[-2], k.shape[-2], q.shape[-1])
        else:
            impl = "xla"
    if impl == "pallas":
        if mask is not None:
            raise ValueError(
                "attention(impl='pallas') does not support dense masks; pass kv_lens "
                "(right-padding) / segment_ids (packing) / causal, or use impl='xla' "
                "for arbitrary masks."
            )
        return flash_attention(q, k, v, kv_lens, segment_ids, causal, sm_scale)
    if impl == "xla":
        if mask is None and kv_lens is not None:
            mask = _kv_lens_to_mask(kv_lens, k.shape[-2])
        return xla_attention(
            q, k, v, mask=mask, causal=causal, sm_scale=sm_scale, segment_ids=segment_ids
        )
    raise ValueError(f"Unknown attention impl {impl!r}; expected 'auto', 'pallas', or 'xla'")
