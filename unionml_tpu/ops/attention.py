"""Attention ops: pallas flash-attention TPU kernel with an XLA fallback.

The reference delegates all math to user frameworks (SURVEY.md §2: "no CUDA/C++
anywhere"); in the TPU rebuild the attention hot op is owned by the framework. Two
implementations behind one dispatcher:

- ``impl="pallas"``: blocked flash attention (online softmax) keeping the working set
  in VMEM, f32 accumulation on the MXU, O(seq) memory. Grid: (batch*heads, q_blocks);
  the KV scan runs inside the kernel with ``jax.lax.fori_loop``.
- ``impl="xla"``: the standard fused-by-XLA softmax(QK^T)V — also the backward path of
  the pallas forward (rematerialized), so autodiff works everywhere.
- ``impl="auto"``: pallas on TPU backends, XLA elsewhere (CPU tests run the fallback).

Shapes follow the (batch, num_heads, seq, head_dim) convention.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: default block sizes — multiples of the MXU/VPU tile (128 lanes)
DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128

_NEG_INF = -1e30


def xla_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """Reference attention; XLA fuses the softmax chain. Used as fallback + backward."""
    *_, seq_q, head_dim = q.shape
    seq_k = k.shape[-2]
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(head_dim)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        causal_mask = jnp.tril(jnp.ones((seq_q, seq_k), dtype=bool))
        logits = jnp.where(causal_mask[None, None], logits, _NEG_INF)
    if mask is not None:
        logits = jnp.where(mask, logits, _NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v.dtype), v)


def _flash_kernel(
    kv_len_ref,
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    *,
    block_k: int,
    seq_k: int,
    causal: bool,
    sm_scale: float,
    block_q: int,
):
    """One (batch*head, q_block) program: stream KV blocks with an online softmax.

    ``kv_len_ref`` is a scalar (SMEM) per-batch valid KV length implementing the
    padding mask: K positions >= kv_len contribute nothing.
    """
    q = q_ref[0].astype(jnp.float32) * sm_scale  # (block_q, head_dim)
    q_index = pl.program_id(1)
    kv_len = kv_len_ref[0]

    acc = jnp.zeros((block_q, q.shape[-1]), dtype=jnp.float32)
    row_max = jnp.full((block_q, 1), _NEG_INF, dtype=jnp.float32)
    row_sum = jnp.zeros((block_q, 1), dtype=jnp.float32)

    num_k_blocks = seq_k // block_k

    def body(k_idx, carry):
        acc, row_max, row_sum = carry
        k_block = k_ref[0, pl.ds(k_idx * block_k, block_k), :].astype(jnp.float32)
        v_block = v_ref[0, pl.ds(k_idx * block_k, block_k), :].astype(jnp.float32)

        scores = jax.lax.dot_general(
            q, k_block, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)

        k_pos = k_idx * block_k + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        valid = k_pos < kv_len
        if causal:
            q_pos = q_index * block_q + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0)
            valid = jnp.logical_and(valid, q_pos >= k_pos)
        scores = jnp.where(valid, scores, _NEG_INF)

        new_max = jnp.maximum(row_max, jnp.max(scores, axis=-1, keepdims=True))
        correction = jnp.exp(row_max - new_max)
        probs = jnp.exp(scores - new_max)
        acc = acc * correction + jax.lax.dot_general(
            probs, v_block, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        row_sum = row_sum * correction + jnp.sum(probs, axis=-1, keepdims=True)
        return acc, new_max, row_sum

    # bound the scan: skip fully-masked KV blocks (padding tail; causal upper triangle)
    last_block = jnp.minimum(num_k_blocks, pl.cdiv(kv_len, block_k))
    if causal:
        last_block = jnp.minimum(last_block, pl.cdiv((q_index + 1) * block_q, block_k))
    acc, row_max, row_sum = jax.lax.fori_loop(0, last_block, body, (acc, row_max, row_sum))
    o_ref[0] = (acc / jnp.maximum(row_sum, 1e-30)).astype(o_ref.dtype)


def _flash_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_lens: Optional[jax.Array],
    causal: bool,
    sm_scale: float,
    block_q: int,
    block_k: int,
    interpret: bool,
) -> jax.Array:
    batch, heads, seq_q, head_dim = q.shape
    seq_k = k.shape[-2]

    # irregular shapes fall back to XLA for exactness; head_dim down to 64 is allowed
    # (mosaic pads the lane dim), smaller/odd head dims are not worth the kernel
    if seq_q % block_q or seq_k % block_k or head_dim % 64:
        mask = _kv_lens_to_mask(kv_lens, seq_k) if kv_lens is not None else None
        return xla_attention(q, k, v, mask=mask, causal=causal, sm_scale=sm_scale)

    bh = batch * heads
    q3 = q.reshape(bh, seq_q, head_dim)
    k3 = k.reshape(bh, seq_k, head_dim)
    v3 = v.reshape(bh, seq_k, head_dim)
    if kv_lens is None:
        kv_lens = jnp.full((batch,), seq_k, dtype=jnp.int32)
    kv_lens_bh = jnp.repeat(kv_lens.astype(jnp.int32), heads)

    kernel = functools.partial(
        _flash_kernel,
        block_k=block_k,
        seq_k=seq_k,
        causal=causal,
        sm_scale=sm_scale,
        block_q=block_q,
    )
    out = pl.pallas_call(
        kernel,
        grid=(bh, seq_q // block_q),
        in_specs=[
            pl.BlockSpec((1,), lambda b, i: (b,), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_k, head_dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_k, head_dim), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq_q, head_dim), q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * seq_q * seq_k * head_dim,
            bytes_accessed=(q3.size + k3.size + v3.size + q3.size) * q3.dtype.itemsize,
            transcendentals=bh * seq_q * seq_k,
        ),
        interpret=interpret,
    )(kv_lens_bh, q3, k3, v3)
    return out.reshape(batch, heads, seq_q, head_dim)


def _kv_lens_to_mask(kv_lens: jax.Array, seq_k: int) -> jax.Array:
    """(batch,) valid lengths -> (batch, 1, 1, seq_k) boolean padding mask."""
    positions = jnp.arange(seq_k)[None, :]
    return (positions < kv_lens[:, None])[:, None, None, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_lens: Optional[jax.Array] = None,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jax.Array:
    """Blocked flash attention (pallas). Differentiable: backward rematerializes via XLA.

    :param kv_lens: optional (batch,) int32 valid KV lengths — the padding-mask case
        (keys at positions >= kv_lens[b] are masked for every head/query of batch b).
    """
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    return _flash_forward(q, k, v, kv_lens, causal, scale, block_q, block_k, interpret)


def _flash_fwd(q, k, v, kv_lens, causal, sm_scale, block_q, block_k, interpret):
    out = flash_attention(q, k, v, kv_lens, causal, sm_scale, block_q, block_k, interpret)
    return out, (q, k, v, kv_lens)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, residuals, g):
    q, k, v, kv_lens = residuals
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    mask = _kv_lens_to_mask(kv_lens, k.shape[-2]) if kv_lens is not None else None
    _, vjp = jax.vjp(
        lambda q_, k_, v_: xla_attention(q_, k_, v_, mask=mask, causal=causal, sm_scale=scale), q, k, v
    )
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def on_tpu() -> bool:
    """True only for genuine TPU devices (incl. remote-TPU plugin backends)."""
    if jax.default_backend() == "tpu":
        return True
    try:
        return "tpu" in jax.devices()[0].device_kind.lower()
    except Exception:  # pragma: no cover - backend without device_kind
        return False


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    kv_lens: Optional[jax.Array] = None,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    impl: str = "auto",
) -> jax.Array:
    """Dispatching attention entrypoint used by the model zoo.

    ``impl="auto"`` picks the pallas kernel on TPU (dense ``mask`` arrays force XLA —
    the kernel handles the causal and per-batch-length padding cases) and the XLA path
    elsewhere.
    """
    if impl == "auto":
        impl = "pallas" if (on_tpu() and mask is None) else "xla"
    if impl == "pallas":
        if mask is not None:
            raise ValueError(
                "attention(impl='pallas') does not support dense masks; pass kv_lens "
                "(right-padding) / causal, or use impl='xla' for arbitrary masks."
            )
        return flash_attention(q, k, v, kv_lens, causal, sm_scale)
    if impl == "xla":
        if mask is None and kv_lens is not None:
            mask = _kv_lens_to_mask(kv_lens, k.shape[-2])
        return xla_attention(q, k, v, mask=mask, causal=causal, sm_scale=sm_scale)
    raise ValueError(f"Unknown attention impl {impl!r}; expected 'auto', 'pallas', or 'xla'")
