"""Sequence packing: several short sequences per training row.

Short-sequence corpora waste most of a fixed-shape batch on padding (a 40-token
example in a 512-token row computes 92% padding). Packing concatenates sequences
into rows and carries ``segment_ids`` so attention stays confined to each
sequence (``ops.attention`` masks cross-segment pairs blockwise in the flash
kernel — no dense (seq, seq) mask) and positions restart per segment
(``models/gpt.py::GPTLMHeadModel``).

This is a capability the reference cannot express at all: its training loop is
whatever the user's ``@model.trainer`` does with torch/sklearn, with no packing
support anywhere (reference ``unionml/dataset.py`` hands frames to user code).

Convention (t5x/flax): segment id 0 = padding, 1..n = packed sequences, ids
restart from 1 in every row. Static shapes throughout — rows are (seq_len,)
always, so one XLA program serves every packed batch.
"""

from typing import Dict, List, Sequence

import numpy as np

__all__ = ["pack_sequences", "packing_efficiency"]


#: corpora at least this large route to the native packer under impl="auto":
#: below it the ctypes marshalling overhead rivals the Python loop's cost
NATIVE_PACK_THRESHOLD = 2048


def pack_sequences(
    sequences: Sequence[np.ndarray],
    seq_len: int,
    *,
    pad_id: int = 0,
    max_segments_per_row: int = 0,
    impl: str = "auto",
) -> Dict[str, np.ndarray]:
    """Greedy first-fit packing of token sequences into fixed-length rows.

    :param sequences: 1-D int token arrays (ragged lengths). Sequences longer
        than ``seq_len`` are truncated to ``seq_len`` (logged in the result's
        ``truncated`` count rather than silently).
    :param seq_len: the packed row length (the compiled program's static shape).
    :param pad_id: token id written into padding slots.
    :param max_segments_per_row: cap on sequences per row (0 = unlimited) — some
        objectives want to bound the in-row mixing.
    :param impl: ``"python"``, ``"native"`` (C++ via
        :func:`unionml_tpu.native.pack_sequences_native`; falls back to Python
        when the toolchain is absent), or ``"auto"`` — native for corpora of
        ``NATIVE_PACK_THRESHOLD``+ sequences. Both paths run the SAME first-fit
        algorithm and produce byte-identical outputs (pinned by tests); native
        exists because the Python loop's O(n_seqs x n_rows) interpreter cost
        dominates job start-up at corpus scale (bench_packing.py measures it).
    :returns: dict with ``input_ids`` (rows, seq_len) int32, ``segment_ids``
        (rows, seq_len) int32 (0 = padding), ``positions`` (rows, seq_len) int32
        (restarting per segment), and ``truncated`` (int) — how many input
        sequences lost tokens to the ``seq_len`` cap.
    """
    if seq_len <= 0:
        raise ValueError(f"seq_len must be positive, got {seq_len}")
    if impl not in ("auto", "python", "native"):
        raise ValueError(f"impl must be 'auto', 'python', or 'native', got {impl!r}")

    # normalize once, shared by both paths: drop empties, truncate overlong
    arrays: List[np.ndarray] = []
    truncated = 0
    for seq in sequences:
        arr = np.asarray(seq).reshape(-1)
        if arr.size == 0:
            continue
        if arr.size > seq_len:
            arr = arr[:seq_len]
            truncated += 1
        arrays.append(arr)

    want_native = impl == "native" or (impl == "auto" and len(arrays) >= NATIVE_PACK_THRESHOLD)
    if want_native:
        from unionml_tpu.native import pack_sequences_native

        lengths = np.asarray([a.size for a in arrays], dtype=np.int64)
        flat = (
            np.concatenate([a.astype(np.int32, copy=False) for a in arrays])
            if arrays
            else np.empty((0,), dtype=np.int32)
        )
        packed = pack_sequences_native(flat, lengths, seq_len, pad_id, max_segments_per_row)
        if packed is not None:
            packed["truncated"] = truncated
            return packed
        # no toolchain: fall through to the Python path

    rows: List[List[np.ndarray]] = []
    row_space: List[int] = []
    row_segments: List[int] = []
    for arr in arrays:
        placed = False
        # first-fit: the earliest row with room (and segment headroom)
        for i in range(len(rows)):
            if row_space[i] >= arr.size and (
                max_segments_per_row <= 0 or row_segments[i] < max_segments_per_row
            ):
                rows[i].append(arr)
                row_space[i] -= arr.size
                row_segments[i] += 1
                placed = True
                break
        if not placed:
            rows.append([arr])
            row_space.append(seq_len - arr.size)
            row_segments.append(1)

    n_rows = max(len(rows), 1)
    input_ids = np.full((n_rows, seq_len), pad_id, dtype=np.int32)
    segment_ids = np.zeros((n_rows, seq_len), dtype=np.int32)
    positions = np.zeros((n_rows, seq_len), dtype=np.int32)
    for r, row in enumerate(rows):
        offset = 0
        for s, arr in enumerate(row, start=1):
            end = offset + arr.size
            input_ids[r, offset:end] = arr
            segment_ids[r, offset:end] = s
            positions[r, offset:end] = np.arange(arr.size)
            offset = end
    return {
        "input_ids": input_ids,
        "segment_ids": segment_ids,
        "positions": positions,
        "truncated": truncated,
    }


def packing_efficiency(segment_ids: np.ndarray) -> float:
    """Fraction of token slots carrying real tokens (1.0 = no padding at all)."""
    total = segment_ids.size
    return float((np.asarray(segment_ids) > 0).sum()) / total if total else 0.0
