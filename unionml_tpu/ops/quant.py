"""Weight-only int8 quantization for inference.

The reference has no quantization story (it serves whatever object the user
trained, `unionml/model.py:1432-1519`); on TPU it is a first-class serving
lever: single-token decode is HBM-bandwidth-bound, and storing weights as int8
halves the bytes each step streams from HBM vs bfloat16. The scheme here is
the standard weight-only recipe:

- **per-output-channel symmetric int8**: each kernel column c stores
  ``round(w[:, c] / scale[c])`` with ``scale[c] = max(|w[:, c]|) / 127``;
- activations stay in the compute dtype — dequantization is one multiply that
  XLA fuses into the consuming matmul, so quality loss is bounded by weight
  rounding only (no activation calibration needed);
- quantized leaves live in the params pytree as :class:`QuantizedArray` nodes
  (a registered pytree), so jit/device_put/checkpoint machinery treats them
  like any other params — they cross host->device as int8 and dequantize
  on-device inside the compiled step.

``quantize_tree`` / ``dequantize_tree`` transform whole pytrees; the decode
engine exposes it as ``DecodeEngine(..., quantize="int8")``.
"""

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "KV_INT8_GREEDY_DIVERGENCE_BUDGET",
    "KV_INT8_LOGPROB_DELTA_BUDGET",
    "QuantizedArray",
    "default_should_quantize",
    "dequantize_blockwise",
    "dequantize_tree",
    "quantize_array",
    "quantize_blockwise",
    "quantize_tree",
    "quantized_bytes",
]

# Pinned quality budgets for the int8 KV block pool, enforced by both the unit
# tests (tests/unit/test_paged_kv.py) and the `bench_serving --int8 ab` gate so
# a regression in either place fails the same numbers. Measured on the tiny CPU
# config with ~3x headroom over observed worst cases; budgets are on the
# pre-divergence prefix (once greedy streams split, the contexts differ and
# per-token comparison stops being meaningful).
KV_INT8_LOGPROB_DELTA_BUDGET = 0.15  # max |Δ logprob| of the bf16-greedy token
KV_INT8_GREEDY_DIVERGENCE_BUDGET = 0.35  # max fraction of tokens past first split


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(eq=False)  # array fields make generated __eq__ raise on bool()
class QuantizedArray:
    """int8 values + per-channel f32 scales standing in for a float array."""

    q: jax.Array  # int8, same shape as the original
    scale: jax.Array  # f32, original shape with the channel axis kept at size 1
    dtype: Any  # dequantization target dtype (the original compute dtype)

    @property
    def shape(self):
        return self.q.shape

    def dequantize(self) -> jax.Array:
        return (self.q.astype(jnp.float32) * self.scale).astype(self.dtype)

    def tree_flatten(self):
        return (self.q, self.scale), self.dtype

    @classmethod
    def tree_unflatten(cls, dtype, children):
        q, scale = children
        return cls(q=q, scale=scale, dtype=dtype)


def quantize_blockwise(x: jax.Array, reduce_axes: Tuple[int, ...]) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 with per-block absmax scales.

    A "block" is one element of the axes NOT in ``reduce_axes``: the absmax
    reduction runs over ``reduce_axes`` (keepdims), ``scale = absmax / 127``,
    and ``q = clip(round(x / scale), -127, 127)``. An all-zero block stores
    ``scale == 0`` — the convention the KV pool relies on so an empty block
    cannot poison the monotone-scale max on its first real write; division is
    guarded internally, and ``dequantize_blockwise`` maps ``q * 0 == 0`` back
    exactly. Round-trip error is bounded by ``scale / 2`` per element.
    """
    x32 = jnp.asarray(x, dtype=jnp.float32)
    absmax = jnp.max(jnp.abs(x32), axis=reduce_axes, keepdims=True)
    scale = absmax / 127.0
    q = jnp.clip(jnp.round(x32 / jnp.where(scale > 0, scale, 1.0)), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_blockwise(q: jax.Array, scale: jax.Array, dtype: Any = jnp.float32) -> jax.Array:
    """Inverse of :func:`quantize_blockwise` (up to rounding): ``q * scale``
    in f32, cast to ``dtype``. Inside jit the multiply fuses into the consumer,
    so int8 is what crosses HBM."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


def quantize_array(w: jax.Array, channel_axis: int = -1) -> QuantizedArray:
    """Symmetric per-channel int8 quantization.

    ``channel_axis`` is the axis whose entries KEEP individual scales (the
    output axis of an (in, out) Dense kernel); the absmax reduction runs over
    every other axis, so ``scale[..., c, ...] = max(|w[..., c, ...]|) / 127``
    and an outlier in one output channel cannot crush the resolution of its
    neighbors."""
    w32 = jnp.asarray(w, dtype=jnp.float32)
    reduce_axes = tuple(i for i in range(w32.ndim) if i != channel_axis % w32.ndim)
    q, scale = quantize_blockwise(w32, reduce_axes)
    # weight trees keep the historical scale==1.0 convention for all-zero
    # channels (dequantize is identical either way; 1.0 keeps scales invertible)
    scale = jnp.where(scale > 0, scale, 1.0)
    return QuantizedArray(q=q, scale=scale, dtype=jnp.asarray(w).dtype)


def default_should_quantize(path: Tuple[str, ...], leaf: Any) -> bool:
    """Quantize 2-D matmul kernels of meaningful size; leave embeddings, norms,
    biases, and tiny projections in full precision.

    Embedding tables are excluded by name (``wte``/``wpe``/``embedding``):
    token embeddings double as the LM head, where per-channel rounding costs
    logit precision directly.
    """
    if not hasattr(leaf, "ndim") or leaf.ndim != 2:
        return False
    if min(leaf.shape) < 64:
        return False
    lowered = "/".join(str(p) for p in path).lower()
    return not any(name in lowered for name in ("wte", "wpe", "embed"))


def quantize_tree(
    params: Any, should_quantize: Optional[Callable[[Tuple[str, ...], Any], bool]] = None
) -> Any:
    """Replace selected leaves with :class:`QuantizedArray` nodes.

    :param should_quantize: ``(path, leaf) -> bool``; defaults to
        :func:`default_should_quantize`.
    """
    pred = should_quantize or default_should_quantize

    def visit(path, leaf):
        keys = tuple(getattr(k, "key", getattr(k, "idx", k)) for k in path)
        return quantize_array(leaf) if pred(keys, leaf) else leaf

    return jax.tree_util.tree_map_with_path(visit, params)


def dequantize_tree(params: Any) -> Any:
    """Materialize full-precision leaves (inside jit: the multiplies fuse into
    the consuming matmuls, so int8 is what crosses HBM)."""
    return jax.tree_util.tree_map(
        lambda leaf: leaf.dequantize() if isinstance(leaf, QuantizedArray) else leaf,
        params,
        is_leaf=lambda leaf: isinstance(leaf, QuantizedArray),
    )


def quantized_bytes(params: Any) -> Tuple[int, int]:
    """(bytes_as_stored, bytes_if_full_precision) across the tree — the HBM
    saving the quantization buys."""
    stored = full = 0
    for leaf in jax.tree_util.tree_leaves(
        params, is_leaf=lambda l: isinstance(l, QuantizedArray)
    ):
        if isinstance(leaf, QuantizedArray):
            stored += leaf.q.size * 1 + leaf.scale.size * 4
            full += leaf.q.size * jnp.dtype(leaf.dtype).itemsize
        elif hasattr(leaf, "size"):
            nbytes = leaf.size * jnp.dtype(leaf.dtype).itemsize
            stored += nbytes
            full += nbytes
    return stored, full
