"""Default resource requests for unionml_tpu stages.

Reference parity: ``unionml/defaults.py:5`` pins ``Resources(cpu="1", mem="1Gi")`` from
flytekit. The rebuild defines its own ``Resources`` spec that is TPU-first: stages may
request a TPU pod-slice (accelerator type + topology) instead of GPUs — this is the
"no GPU in the task spec" north-star requirement (BASELINE.json).
"""

from dataclasses import dataclass, field
from typing import Optional, Tuple


@dataclass(frozen=True)
class Resources:
    """Resource request attached to a stage / job spec.

    ``accelerator`` uses TPU accelerator-type strings (e.g. ``"v5litepod-8"``) as used by
    TPU VM / GKE node-pool provisioning; ``topology`` is the chip topology (e.g. ``"2x4"``).
    ``host_count`` > 1 indicates a multi-host slice requiring ``jax.distributed`` init.
    """

    cpu: str = "1"
    mem: str = "1Gi"
    accelerator: Optional[str] = None
    topology: Optional[str] = None
    host_count: int = 1

    @property
    def device_count(self) -> int:
        """Number of chips implied by ``topology`` (e.g. "2x4" -> 8); 0 when no accelerator."""
        if self.accelerator is None:
            return 0
        if self.topology is None:
            return 1
        count = 1
        for dim in self.topology.lower().split("x"):
            count *= int(dim)
        return count

    def mesh_axes(self) -> Tuple[int, ...]:
        """Topology dims as a tuple usable to build a device mesh."""
        if self.topology is None:
            return (max(self.device_count, 1),)
        return tuple(int(dim) for dim in self.topology.lower().split("x"))


DEFAULT_RESOURCES = Resources(cpu="1", mem="1Gi")

#: Single-host v5e-8 slice — the baseline data-parallel target (BASELINE.md).
TPU_V5E_8 = Resources(cpu="8", mem="16Gi", accelerator="v5litepod-8", topology="2x4", host_count=1)

#: Single v5e chip — serving target.
TPU_V5E_1 = Resources(cpu="4", mem="8Gi", accelerator="v5litepod-1", topology="1x1", host_count=1)
