"""Expert parallelism: a mixture-of-experts layer sharded over an ``"expert"`` mesh axis.

Each device owns ``experts_per_device`` expert MLPs (parameters sharded on their
leading expert axis); tokens are routed top-1 by an external gating assignment. The
dispatch is dense-masked: every device computes its local experts over the full token
set, masks by assignment, and a ``psum`` over the expert axis combines the shards —
the simplest exact EP layout (all-to-all token dispatch is the optimization, not a
semantic change; queued as future work in NEXT.md).
"""

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

EXPERT_AXIS = "expert"


def _moe_local(expert_params, tokens, assignment, *, expert_fn, axis_name: str, experts_per_device: int):
    """Per-device body: run local experts on all tokens, mask, combine via psum."""
    device_index = lax.axis_index(axis_name)
    out = jnp.zeros(tokens.shape[:-1] + (_out_dim(expert_fn, expert_params, tokens),), dtype=tokens.dtype)
    for local_e in range(experts_per_device):
        global_e = device_index * experts_per_device + local_e
        params_e = jax.tree_util.tree_map(lambda p: p[local_e], expert_params)
        expert_out = expert_fn(params_e, tokens)
        mask = (assignment == global_e)[..., None].astype(tokens.dtype)
        out = out + expert_out * mask
    return lax.psum(out, axis_name)


def _out_dim(expert_fn, expert_params, tokens):
    params_0 = jax.tree_util.tree_map(lambda p: p[0], expert_params)
    return jax.eval_shape(expert_fn, params_0, tokens).shape[-1]


def moe_apply(
    expert_fn: Callable,
    stacked_params: Any,
    tokens: jax.Array,
    assignment: jax.Array,
    mesh: Mesh,
    *,
    axis: str = EXPERT_AXIS,
) -> jax.Array:
    """Apply a top-1-routed mixture of experts sharded over ``axis``.

    :param expert_fn: ``(params, tokens) -> outputs`` applied per expert.
    :param stacked_params: pytree with a leading ``num_experts`` axis; sharded over
        ``axis`` (``num_experts`` must divide by the axis size).
    :param tokens: (..., d_model) token activations (replicated).
    :param assignment: (...,) int32 expert index per token (the router's argmax).
    """
    num_devices = mesh.shape[axis]
    num_experts = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if num_experts % num_devices:
        raise ValueError(
            f"num_experts ({num_experts}) must be divisible by the {axis!r} axis size ({num_devices})"
        )
    experts_per_device = num_experts // num_devices

    params_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    body = functools.partial(
        _moe_local, expert_fn=expert_fn, axis_name=axis, experts_per_device=experts_per_device
    )
    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(params_spec, P(), P()),
        out_specs=P(),
        check_vma=False,
    )(stacked_params, tokens, assignment)


def expert_sharding(mesh: Mesh, axis: str = EXPERT_AXIS) -> NamedSharding:
    """Sharding for stacked per-expert parameters (leading expert axis)."""
    return NamedSharding(mesh, P(axis))
