"""Expert parallelism: a mixture-of-experts layer sharded over an ``"expert"`` mesh axis.

Each device owns ``experts_per_device`` expert MLPs (parameters sharded on their
leading expert axis). Three dispatch formulations, in increasing scalability:

- :func:`moe_apply` — dense-masked top-1: every device computes its local experts
  over the FULL token set, masks by assignment, ``psum`` combines. O(experts_per_device
  x total_tokens) overcompute; the exactness oracle the scalable paths are tested
  against, and fine at testbench scale.
- :func:`moe_apply_topk` / :func:`moe_apply_capacity` — GShard capacity dispatch via
  one-hot einsums with ``expert``-axis sharding constraints; XLA infers the
  collectives. The (tokens, experts, capacity) dispatch tensors are still global.
- :func:`moe_apply_a2a` — explicit ``shard_map`` + ``lax.all_to_all`` token dispatch:
  tokens are sharded, each device routes only its local tokens into per-expert
  capacity buffers, and two all-to-alls (dispatch + return) ride the ICI. Per-device
  compute and memory are O(num_experts x capacity) ~ O(local_tokens x k x
  capacity_factor), independent of the global token count — the pod-scale layout.
"""

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from unionml_tpu.parallel._compat import shard_map

EXPERT_AXIS = "expert"


def _moe_local(expert_params, tokens, assignment, *, expert_fn, axis_name: str, experts_per_device: int):
    """Per-device body: run local experts on all tokens, mask, combine via psum."""
    device_index = lax.axis_index(axis_name)
    out = jnp.zeros(tokens.shape[:-1] + (_out_dim(expert_fn, expert_params, tokens),), dtype=tokens.dtype)
    for local_e in range(experts_per_device):
        global_e = device_index * experts_per_device + local_e
        params_e = jax.tree_util.tree_map(lambda p: p[local_e], expert_params)
        expert_out = expert_fn(params_e, tokens)
        mask = (assignment == global_e)[..., None].astype(tokens.dtype)
        out = out + expert_out * mask
    return lax.psum(out, axis_name)


def _out_dim(expert_fn, expert_params, tokens):
    params_0 = jax.tree_util.tree_map(lambda p: p[0], expert_params)
    return jax.eval_shape(expert_fn, params_0, tokens).shape[-1]


def moe_apply(
    expert_fn: Callable,
    stacked_params: Any,
    tokens: jax.Array,
    assignment: jax.Array,
    mesh: Mesh,
    *,
    axis: str = EXPERT_AXIS,
) -> jax.Array:
    """Apply a top-1-routed mixture of experts sharded over ``axis``.

    :param expert_fn: ``(params, tokens) -> outputs`` applied per expert.
    :param stacked_params: pytree with a leading ``num_experts`` axis; sharded over
        ``axis`` (``num_experts`` must divide by the axis size).
    :param tokens: (..., d_model) token activations (replicated).
    :param assignment: (...,) int32 expert index per token (the router's argmax).
    """
    num_devices = mesh.shape[axis]
    num_experts = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if num_experts % num_devices:
        raise ValueError(
            f"num_experts ({num_experts}) must be divisible by the {axis!r} axis size ({num_devices})"
        )
    experts_per_device = num_experts // num_devices

    params_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    body = functools.partial(
        _moe_local, expert_fn=expert_fn, axis_name=axis, experts_per_device=experts_per_device
    )
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(params_spec, P(), P()),
        out_specs=P(),
        check_vma=False,
    )(stacked_params, tokens, assignment)


def expert_sharding(mesh: Mesh, axis: str = EXPERT_AXIS) -> NamedSharding:
    """Sharding for stacked per-expert parameters (leading expert axis)."""
    return NamedSharding(mesh, P(axis))


def moe_apply_capacity(
    expert_fn: Callable,
    stacked_params: Any,
    tokens: jax.Array,
    gates: jax.Array,
    mesh: Mesh,
    *,
    capacity_factor: float = 1.25,
    axis: str = EXPERT_AXIS,
) -> jax.Array:
    """GShard-style capacity-based top-1 MoE: sharding constraints, XLA collectives.

    Unlike :func:`moe_apply` (dense-masked, every device computes all tokens), this
    formulation dispatches each token into its expert's fixed-capacity buffer via
    one-hot einsums; expert buffers carry an ``expert``-axis sharding constraint, so
    under ``jit`` XLA inserts the all-to-alls that move only each expert's tokens to
    its device. Tokens beyond an expert's capacity are DROPPED (output zero) — the
    standard GShard trade-off; size ``capacity_factor`` accordingly.

    :param gates: (tokens, num_experts) router probabilities (e.g. softmax output);
        the top-1 expert's gate value scales its output (straight-through routing).
    :returns: (tokens, d_out) combined expert outputs.
    """
    # exactly the k=1 special case of the top-k dispatch: argmax == top_k(1) (both
    # break ties toward the lower index) and the unnormalized top-1 gate is the
    # plain gate value — one implementation, one place to fix routing bugs
    return moe_apply_topk(
        expert_fn,
        stacked_params,
        tokens,
        gates,
        mesh,
        k=1,
        capacity_factor=capacity_factor,
        normalize_gates=False,
        axis=axis,
    )


def moe_apply_topk(
    expert_fn: Callable,
    stacked_params: Any,
    tokens: jax.Array,
    gates: jax.Array,
    mesh: Optional[Mesh] = None,
    *,
    k: int = 2,
    capacity_factor: Optional[float] = 1.25,
    normalize_gates: bool = True,
    axis: str = EXPERT_AXIS,
) -> jax.Array:
    """GShard top-k (default top-2) capacity-based MoE dispatch.

    ``capacity_factor=None`` is DROPLESS: the dispatch switches to the dense-masked
    formulation (every expert computes every token, top-k gates select) so no token
    ever loses a routed choice regardless of router imbalance — the inference-parity
    mode. Costs E x redundant expert compute; use the factor-bounded mode for
    training efficiency.

    Generalizes :func:`moe_apply_capacity` to k routed experts per token: each token
    claims up to ``k`` expert-buffer slots, choice-major — every token's FIRST choice
    is assigned buffer positions before any second choice, so overflow drops lower-
    priority choices first (the GShard ordering). Combined output is the gate-weighted
    sum over surviving choices; ``normalize_gates`` renormalizes over the top-k
    (the standard top-2 formulation).

    With a ``mesh``, expert buffers carry ``axis`` sharding constraints, so under
    ``jit`` XLA inserts the all-to-alls that move only each expert's tokens to its
    device; ``mesh=None`` runs the same dispatch unsharded (single-device layers,
    e.g. :class:`unionml_tpu.models.moe.MoEMlp` without expert parallelism).
    """
    num_tokens, num_experts = gates.shape
    params_experts = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if params_experts != num_experts:
        raise ValueError(
            f"gates are over {num_experts} experts but stacked_params carries {params_experts}"
        )
    if mesh is not None and num_experts % mesh.shape[axis]:
        raise ValueError(
            f"num_experts ({num_experts}) must be divisible by the {axis!r} axis size "
            f"({mesh.shape[axis]})"
        )
    if not 1 <= k <= num_experts:
        raise ValueError(f"k ({k}) must be in [1, num_experts={num_experts}]")

    top_gates, top_index = jax.lax.top_k(gates, k)  # (t, k)
    if normalize_gates:
        top_gates = top_gates / jnp.maximum(jnp.sum(top_gates, axis=-1, keepdims=True), 1e-9)

    if capacity_factor is None:
        # dropless via the dense-masked formulation (same shape as _moe_local):
        # every expert computes every token — E x redundant compute, O(E * T * d)
        # memory — and the top-k gates select/weight per token. Exact for any
        # router state; far cheaper than capacity=num_tokens buffers (O(E * T^2)).
        all_out = jax.vmap(expert_fn, in_axes=(0, None))(stacked_params, tokens)  # (e, t, d_out)
        if mesh is not None:
            all_out = jax.lax.with_sharding_constraint(
                all_out, NamedSharding(mesh, P(axis, None, None))
            )
        one_hot_k = jax.nn.one_hot(top_index, num_experts, dtype=tokens.dtype)  # (t, k, e)
        weights = jnp.einsum("tke,tk->te", one_hot_k, top_gates.astype(tokens.dtype))
        out = jnp.einsum("te,etd->td", weights, all_out.astype(tokens.dtype))
        return out.astype(tokens.dtype)

    capacity = max(int(np.ceil(num_tokens * k / num_experts * capacity_factor)), 1)

    dispatch, combine = _topk_dispatch_combine(
        top_index, top_gates, num_experts, capacity, tokens.dtype
    )

    expert_inputs = jnp.einsum("tec,td->ecd", dispatch, tokens)  # (e, c, d)
    if mesh is not None:
        expert_inputs = jax.lax.with_sharding_constraint(
            expert_inputs, NamedSharding(mesh, P(axis, None, None))
        )
    expert_outputs = jax.vmap(expert_fn)(stacked_params, expert_inputs)  # (e, c, d_out)
    if mesh is not None:
        expert_outputs = jax.lax.with_sharding_constraint(
            expert_outputs, NamedSharding(mesh, P(axis, None, None))
        )

    out = jnp.einsum("tec,ecd->td", combine, expert_outputs.astype(tokens.dtype))
    return out.astype(tokens.dtype)


def _topk_dispatch_combine(top_index, top_gates, num_experts: int, capacity: int, dtype):
    """(t, k) top-k routing -> (t, e, c) dispatch / combine tensors.

    Choice-major position assignment: flatten to (k * t, e) with choice 0 first so
    first choices never lose a buffer slot to someone's second choice (int32: a
    low-precision cumsum would corrupt routing past 256 tokens per expert). The
    position one-hot zeroes slots >= capacity — that IS the drop.
    """
    num_tokens, k = top_index.shape
    one_hot_i = jax.nn.one_hot(top_index, num_experts, dtype=jnp.int32)  # (t, k, e)
    choice_major = jnp.swapaxes(one_hot_i, 0, 1).reshape(k * num_tokens, num_experts)
    positions_flat = jnp.sum(
        (jnp.cumsum(choice_major, axis=0) - choice_major) * choice_major, axis=-1
    )  # (k * t,)
    position = jnp.swapaxes(positions_flat.reshape(k, num_tokens), 0, 1)  # (t, k)

    one_hot = one_hot_i.astype(dtype)  # (t, k, e)
    position_one_hot = jax.nn.one_hot(position, capacity, dtype=dtype)  # (t, k, c)
    dispatch = jnp.einsum("tke,tkc->tec", one_hot, position_one_hot)
    combine = jnp.einsum("tke,tkc,tk->tec", one_hot, position_one_hot, top_gates.astype(dtype))
    return dispatch, combine


def _moe_a2a_local(
    local_params,
    tokens,
    gates,
    *,
    expert_fn,
    axis_name: str,
    num_experts: int,
    experts_per_device: int,
    k: int,
    capacity: int,
    normalize_gates: bool,
):
    """Per-device body of :func:`moe_apply_a2a` (tokens/gates are LOCAL shards).

    Buffer layout through the exchange: ``send`` is (num_experts, capacity, d)
    ordered by GLOBAL expert index; grouped as (ep_degree, experts_per_device *
    capacity, d) a tiled ``all_to_all`` delivers group j to device j, so each
    device receives (ep_degree, experts_per_device, capacity, d) = every source
    device's buffers for ITS experts. The return trip applies the inverse
    transpose, and the combine einsum runs on the token's home device.
    """
    ep_degree = num_experts // experts_per_device
    d_model = tokens.shape[-1]

    top_gates, top_index = jax.lax.top_k(gates, k)  # (t_local, k)
    if normalize_gates:
        top_gates = top_gates / jnp.maximum(jnp.sum(top_gates, axis=-1, keepdims=True), 1e-9)
    dispatch, combine = _topk_dispatch_combine(
        top_index, top_gates, num_experts, capacity, tokens.dtype
    )

    send = jnp.einsum("tec,td->ecd", dispatch, tokens)  # (E, c, d): my tokens, bucketed
    send = send.reshape(ep_degree, experts_per_device * capacity, d_model)
    recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=0, tiled=True)
    # (src_device, experts_per_device, c, d) -> (experts_per_device, src * c, d)
    expert_inputs = (
        recv.reshape(ep_degree, experts_per_device, capacity, d_model)
        .transpose(1, 0, 2, 3)
        .reshape(experts_per_device, ep_degree * capacity, d_model)
    )

    expert_outputs = jax.vmap(expert_fn)(local_params, expert_inputs)
    d_out = expert_outputs.shape[-1]

    back = (
        expert_outputs.reshape(experts_per_device, ep_degree, capacity, d_out)
        .transpose(1, 0, 2, 3)
        .reshape(ep_degree, experts_per_device * capacity, d_out)
    )
    returned = lax.all_to_all(back, axis_name, split_axis=0, concat_axis=0, tiled=True)
    returned = returned.reshape(num_experts, capacity, d_out)  # my tokens' outputs, by expert

    out = jnp.einsum("tec,ecd->td", combine, returned.astype(tokens.dtype))
    return out.astype(tokens.dtype)


def moe_apply_a2a(
    expert_fn: Callable,
    stacked_params: Any,
    tokens: jax.Array,
    gates: jax.Array,
    mesh: Mesh,
    *,
    k: int = 2,
    capacity_factor: float = 1.25,
    normalize_gates: bool = True,
    axis: str = EXPERT_AXIS,
    data_axis: Optional[str] = "data",
) -> jax.Array:
    """Top-k MoE with explicit ``lax.all_to_all`` token dispatch (the pod-scale path).

    Tokens are sharded over ``(data_axis, axis)`` (or just ``axis`` when the mesh has
    no ``data_axis``); each device routes ONLY its local tokens into per-expert
    capacity buffers, one all-to-all over the expert axis moves each buffer to the
    device owning that expert, local experts run on (experts_per_device, ep_degree *
    capacity) batches, and a second all-to-all returns outputs to each token's home
    device for the gate-weighted combine. Per-device compute is O(num_experts x
    capacity) ~ O(local_tokens x k x capacity_factor) — independent of the global
    token count, unlike :func:`moe_apply`'s dense-masked formulation.

    Capacity is granted PER (source device, expert): ``ceil(local_tokens * k /
    num_experts * capacity_factor)`` slots for each expert on each source shard.
    Routing therefore drops a choice only when one shard's local demand for one
    expert overflows — global capacity scales with the EP degree, so for a given
    ``capacity_factor`` this drops at most as often as :func:`moe_apply_topk`'s
    global budget when token shards are balanced (the DP-sharded training case).
    Exact parity with the dense oracle holds whenever nothing drops (tested).

    :param tokens: (num_tokens, d_model), dim 0 divisible by the token-shard count.
    :param gates: (num_tokens, num_experts) router probabilities, sharded like
        ``tokens``.
    """
    num_tokens, num_experts = gates.shape
    params_experts = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if params_experts != num_experts:
        raise ValueError(
            f"gates are over {num_experts} experts but stacked_params carries {params_experts}"
        )
    ep_degree = mesh.shape[axis]
    if num_experts % ep_degree:
        raise ValueError(
            f"num_experts ({num_experts}) must be divisible by the {axis!r} axis size ({ep_degree})"
        )
    if not 1 <= k <= num_experts:
        raise ValueError(f"k ({k}) must be in [1, num_experts={num_experts}]")
    token_axes = (data_axis, axis) if data_axis and data_axis in mesh.shape else (axis,)
    shard_count = int(np.prod([mesh.shape[a] for a in token_axes]))
    if num_tokens % shard_count:
        raise ValueError(
            f"num_tokens ({num_tokens}) must be divisible by the token-shard count "
            f"({shard_count}: mesh axes {token_axes})"
        )
    t_local = num_tokens // shard_count
    capacity = max(int(np.ceil(t_local * k / num_experts * capacity_factor)), 1)

    params_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    body = functools.partial(
        _moe_a2a_local,
        expert_fn=expert_fn,
        axis_name=axis,
        num_experts=num_experts,
        experts_per_device=num_experts // ep_degree,
        k=k,
        capacity=capacity,
        normalize_gates=normalize_gates,
    )
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(params_spec, P(token_axes), P(token_axes)),
        out_specs=P(token_axes),
        check_vma=False,
    )(stacked_params, tokens, gates)
