"""Data-parallel training: pjit a train step over a mesh with batch sharding.

This is the north-star DP engine (SURVEY.md §2 parallelism table): the ``Dataset``
splitter's output is laid onto the mesh's ``"data"`` axis; gradients reduce over ICI via
the ``psum`` XLA inserts for the replicated-output constraint — no hand-written
collectives, no NCCL analogue.

The canonical usage inside a ``@model.trainer`` function::

    step = data_parallel_step(train_step, mesh)   # once, outside the loop
    for batch in batches(X, y, batch_size):
        state, metrics = step(state, batch)       # donated state, sharded batch
"""

from typing import Any, Callable, Iterator, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from unionml_tpu.parallel.mesh import (
    DATA_AXIS,
    batch_axis_size,
    batch_sharding,
    make_mesh,
    replicated,
    wrapped_row_indices,
)


def data_parallel_step(
    step_fn: Callable,
    mesh: Optional[Mesh] = None,
    *,
    batch_axis: str = DATA_AXIS,
    donate_state: bool = True,
) -> Callable:
    """Compile ``step_fn(state, batch) -> (state, aux)`` for data-parallel execution.

    ``state`` is replicated (or FSDP-sharded if its arrays carry shardings already);
    ``batch`` is sharded along the leading dimension. Donating the state lets XLA reuse
    its HBM buffers across steps — essential at BERT-base scale.
    """
    mesh = mesh or make_mesh()
    state_sharding = replicated(mesh)
    batch_shd = batch_sharding(mesh, batch_axis)

    return jax.jit(
        step_fn,
        in_shardings=(state_sharding, batch_shd),
        out_shardings=None,
        donate_argnums=(0,) if donate_state else (),
    )


def data_parallel_eval(
    eval_fn: Callable,
    mesh: Optional[Mesh] = None,
    *,
    batch_axis: str = DATA_AXIS,
) -> Callable:
    """Compile ``eval_fn(state, batch) -> metrics`` with batch sharding, no donation."""
    mesh = mesh or make_mesh()
    return jax.jit(
        eval_fn,
        in_shardings=(replicated(mesh), batch_sharding(mesh, batch_axis)),
    )


def batches(
    *arrays: Any,
    batch_size: int,
    rng: Optional[np.random.Generator] = None,
    drop_remainder: bool = True,
    mesh: Optional[Mesh] = None,
) -> Iterator[Tuple[Any, ...]]:
    """Host-side batch iterator; optionally lays each batch onto the mesh.

    With a mesh, each yielded batch is ``device_put`` with data-axis sharding so the
    subsequent jit call does zero host transfers. ``drop_remainder`` keeps shapes static
    (one compiled executable for the whole epoch).
    """
    host_arrays = tuple(np.asarray(a) for a in arrays)  # one host copy, not one per batch
    n_rows = host_arrays[0].shape[0]
    indices = np.arange(n_rows) if rng is None else rng.permutation(n_rows)
    end = (n_rows // batch_size) * batch_size if drop_remainder else n_rows
    if end == 0:
        end = n_rows  # degenerate tiny datasets: yield one short batch
    axis_size = batch_axis_size(mesh) if mesh is not None else 1
    for start in range(0, end, batch_size):
        batch_idx = indices[start : start + batch_size]
        if mesh is not None:
            # ragged final/degenerate batches must still divide the sharded axes;
            # wrap real row indices to fill (see wrapped_row_indices)
            wrap = wrapped_row_indices(len(batch_idx), axis_size)
            if wrap is not None:
                batch_idx = batch_idx[wrap]
        batch = tuple(a[batch_idx] for a in host_arrays)
        if mesh is not None:
            sharding = batch_sharding(mesh)
            batch = tuple(jax.device_put(b, sharding) for b in batch)
        yield batch if len(batch) > 1 else batch[0]


def pad_to_multiple(array: Any, multiple: int, axis: int = 0, pad_value: float = 0.0) -> Tuple[Any, int]:
    """Pad ``axis`` up to a multiple (device count / bucket size); returns (padded, original_len).

    Static-shape helper for sharded inference: the batch dim must divide the mesh's data
    axis, so ragged final batches pad up and the caller slices the result back down.
    """
    array = np.asarray(array) if not isinstance(array, jax.Array) else array
    length = array.shape[axis]
    remainder = length % multiple
    if remainder == 0:
        return array, length
    pad_width = [(0, 0)] * array.ndim
    pad_width[axis] = (0, multiple - remainder)
    if isinstance(array, jax.Array):
        import jax.numpy as jnp

        return jnp.pad(array, pad_width, constant_values=pad_value), length
    return np.pad(array, pad_width, constant_values=pad_value), length
