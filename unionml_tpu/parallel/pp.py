"""Pipeline parallelism: microbatch pipelining over a ``"stage"`` mesh axis.

Homogeneous-stage pipelining (the transformer-layers case): per-stage parameters are
stacked on a leading axis and sharded over ``stage``; microbatches flow device-to-device
via ``lax.ppermute`` (ICI neighbor exchange). The schedule runs
``num_microbatches + num_stages - 1`` ticks; at tick t, stage s computes microbatch
``t - s`` (classic fill/steady/drain).

**Stage-local buffers** (round-2; round 1 replicated them O(batch) per device): the
microbatch input buffer is SHARDED over the stage axis and left-rotates one slot per
tick, so stage 0 always finds the next microbatch in its local slot 0 — per-device
input memory is O(batch / num_stages). Outputs are collected symmetrically into a
stage-sharded left-rotating buffer that lands microbatch j in global slot j on the
final tick. Each rotation moves one microbatch over ICI and overlaps with the tick's
stage compute under XLA's scheduler.

**Backward** is the transpose of this schedule: differentiating the scan yields a
reverse pipeline (``ppermute`` transposes to the opposite permutation), i.e. B runs
after F per microbatch with the same bubble fraction — the GPipe-equivalent reverse
schedule. A hand-interleaved 1F1B would need per-stage divergent control flow inside
one SPMD program, which XLA lowers to select(both-branches) — ~1.5x the compute of the
transposed schedule — so the TPU-idiomatic memory lever is rematerialization instead:
``remat=True`` wraps the stage body in ``jax.checkpoint``, bounding saved activations
to one microbatch input per tick (O(batch/num_microbatches) working set per stage)
while the backward recomputes stage internals on the fly. (This is the same stance the
public praxis/GSPMD pipelining layers take on TPU.)

SURVEY.md §2 marks PP "future work" for the reference rebuild; here it lands as a
composable primitive (the dryrun exercises it alongside dp/fsdp/tp/sp/ep).
"""

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from unionml_tpu.parallel._compat import shard_map

STAGE_AXIS = "stage"


def _pipeline_local(stage_params, inp, *, stage_fn, axis_name: str, num_microbatches: int):
    """Per-device schedule with stage-sharded rotating input/output buffers.

    ``inp``: (K, mb, ...) — this device's shard of the (M, mb, ...) microbatch stack.
    Per tick: stage 0 consumes its local slot 0 (the left-rotation below guarantees
    global microbatch t sits there at tick t); every stage computes; the activation
    hands off rightward; both buffers left-rotate one slot around the ring.
    """
    num_stages = lax.psum(1, axis_name)
    stage_index = lax.axis_index(axis_name)
    stage_params = jax.tree_util.tree_map(lambda p: p[0], stage_params)  # drop stage dim

    k_local = inp.shape[0]  # num_microbatches // num_stages
    mb_shape = inp.shape[1:]
    outputs = jnp.zeros((k_local,) + mb_shape, dtype=inp.dtype)
    carry = jnp.zeros(mb_shape, dtype=inp.dtype)
    handoff = [(i, i + 1) for i in range(num_stages - 1)]
    rotate_left = [(i, (i - 1) % num_stages) for i in range(num_stages)]

    def rotate(buf):
        # global slot p -> p-1 (mod M): first local slot moves to the previous
        # device's last slot; the rest shift down locally
        recv = lax.ppermute(buf[0], axis_name, rotate_left)
        return jnp.concatenate([buf[1:], recv[None]], axis=0)

    def tick(t, state):
        outputs, carry, inp = state
        # stage 0 consumes the microbatch the rotation delivered to its slot 0
        h_in = jnp.where(stage_index == 0, inp[0], carry)
        h_out = stage_fn(stage_params, h_in)
        inp = rotate(inp)
        # collect at the last stage once the pipeline has filled (t >= num_stages-1):
        # rotate first, then write into the LAST global slot; the remaining
        # M-1-j rotations walk microbatch j's output to global slot j
        outputs = rotate(outputs)
        is_output_tick = jnp.logical_and(stage_index == num_stages - 1, t >= num_stages - 1)
        outputs = jnp.where(is_output_tick, outputs.at[k_local - 1].set(h_out), outputs)
        carry = lax.ppermute(h_out, axis_name, handoff)
        return outputs, carry, inp

    total_ticks = num_microbatches + num_stages - 1
    outputs, _, _ = lax.fori_loop(0, total_ticks, tick, (outputs, carry, inp), unroll=False)
    return outputs


def pipeline_apply(
    stage_fn: Callable,
    stacked_params: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    num_microbatches: int,
    axis: str = STAGE_AXIS,
    remat: bool = False,
) -> jax.Array:
    """Apply ``num_stages`` instances of ``stage_fn`` as a microbatch pipeline.

    :param stage_fn: ``(params, h) -> h`` with matching input/output shapes
        (homogeneous stages — the stacked-transformer-layers case).
    :param stacked_params: pytree whose leaves carry a leading ``num_stages`` axis,
        sharded over ``axis``.
    :param x: (batch, ...) input; ``num_microbatches`` must evenly divide ``batch``,
        and the ``axis`` mesh size must evenly divide ``num_microbatches`` (the
        microbatch stack is sharded over the stage axis — O(batch/num_stages)
        input memory per device instead of a replicated O(batch) buffer).
    :param num_microbatches: pipeline fill granularity; per-tick compute per stage
        scales with ``batch / num_microbatches`` while bubble fraction scales with
        ``(num_stages - 1) / (num_microbatches + num_stages - 1)``.
    :param remat: rematerialize stage bodies in the backward pass
        (``jax.checkpoint``) — saved residuals shrink to the per-tick microbatch
        inputs; stage internals recompute during the reverse schedule.
    :returns: (batch, ...) output, microbatch-sharded over the stage axis.
    """
    num_stages = mesh.shape[axis]
    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(
            f"num_microbatches ({num_microbatches}) must evenly divide batch ({batch})"
        )
    if num_microbatches % num_stages:
        raise ValueError(
            f"the {axis!r} mesh axis size ({num_stages}) must evenly divide "
            f"num_microbatches ({num_microbatches}) — the microbatch stack is sharded "
            f"over the stage axis"
        )
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != num_stages:
            raise ValueError(
                f"stacked_params leading axis ({leaf.shape[0]}) must equal the {axis!r} "
                f"mesh axis size ({num_stages})"
            )

    x_mb = x.reshape((num_microbatches, batch // num_microbatches) + x.shape[1:])

    body_fn = jax.checkpoint(stage_fn) if remat else stage_fn
    params_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    body = functools.partial(
        _pipeline_local, stage_fn=body_fn, axis_name=axis, num_microbatches=num_microbatches
    )
    out_mb = shard_map(
        body,
        mesh=mesh,
        in_specs=(params_spec, P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )(stacked_params, x_mb)
    return out_mb.reshape((batch,) + x.shape[1:])


def _circular_local(
    stage_params, inp, *, stage_fn, axis_name: str, num_microbatches: int, rounds: int
):
    """Per-device circular (interleaved) schedule.

    Device d owns ``rounds`` stage-chunks: virtual stages d, d+D, d+2D, … of an
    L = rounds*D virtual pipeline. Activations hand off around a RING (device
    D-1 wraps to device 0 with the round index advancing), and each tick a
    device applies the chunk its current job calls for via a dynamic index into
    its stacked chunk params — same SPMD program on every device, no divergent
    control flow. Job timing: device d's j-th busy tick (j = t - d) runs chunk
    ``(j // D) % rounds`` for microbatch ``(j // (rounds*D))*D + j % D``;
    total ticks = M*rounds + D - 1, so the fill/drain bubble is
    (D-1)/(M*rounds + D-1) — ``rounds`` times smaller than blocking the same
    layers into superstages.
    """
    num_devices = lax.psum(1, axis_name)
    device_index = lax.axis_index(axis_name)
    stage_params = jax.tree_util.tree_map(lambda p: p[0], stage_params)  # (rounds, ...)

    k_local = inp.shape[0]  # num_microbatches // num_devices
    mb_shape = inp.shape[1:]
    outputs = jnp.zeros((k_local,) + mb_shape, dtype=inp.dtype)
    carry = jnp.zeros(mb_shape, dtype=inp.dtype)
    ring = [(i, (i + 1) % num_devices) for i in range(num_devices)]
    rotate_left = [(i, (i - 1) % num_devices) for i in range(num_devices)]
    total_jobs = num_microbatches * rounds

    def rotate(buf):
        recv = lax.ppermute(buf[0], axis_name, rotate_left)
        return jnp.concatenate([buf[1:], recv[None]], axis=0)

    def tick(t, state):
        outputs, carry, inp = state
        job = jnp.clip(t - device_index, 0, total_jobs - 1)
        active = jnp.logical_and(t >= device_index, t - device_index < total_jobs)
        chunk = (job // num_devices) % rounds
        params_c = jax.tree_util.tree_map(
            lambda p: lax.dynamic_index_in_dim(p, chunk, 0, keepdims=False), stage_params
        )
        consume_new = jnp.logical_and(jnp.logical_and(active, device_index == 0), chunk == 0)
        h_in = jnp.where(consume_new, inp[0], carry)
        h_out = stage_fn(params_c, h_in)

        # buffer rotations are collectives selected by TICK-ONLY predicates, so
        # every device adopts (or discards) a rotation on the same ticks and the
        # ring contents stay globally consistent
        consume_tick = jnp.logical_and(t < total_jobs, (t // num_devices) % rounds == 0)
        inp = jnp.where(consume_tick, rotate(inp), inp)

        out_job = t - (num_devices - 1)
        write_tick = jnp.logical_and(
            jnp.logical_and(out_job >= 0, out_job < total_jobs),
            (out_job // num_devices) % rounds == rounds - 1,
        )
        rotated = rotate(outputs)
        written = jnp.where(
            device_index == num_devices - 1, rotated.at[k_local - 1].set(h_out), rotated
        )
        outputs = jnp.where(write_tick, written, outputs)

        carry = lax.ppermute(h_out, axis_name, ring)
        return outputs, carry, inp

    total_ticks = total_jobs + num_devices - 1
    outputs, _, _ = lax.fori_loop(0, total_ticks, tick, (outputs, carry, inp), unroll=False)
    return outputs


def pipeline_apply_circular(
    stage_fn: Callable,
    stacked_params: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    num_microbatches: int,
    rounds: int,
    axis: str = STAGE_AXIS,
    remat: bool = False,
) -> jax.Array:
    """Circular (interleaved) pipeline: ``rounds`` stage-chunks per device.

    The virtual pipeline has ``rounds * mesh.shape[axis]`` stages applied in
    sequence; device d holds chunks d, d+D, d+2D, … stacked on a ``rounds``
    axis, and a microbatch wraps around the device ring ``rounds`` times
    (Megatron's interleaved schedule, praxis's circular pipeline). Compared to
    blocking the same layers into :func:`superstage` groups, parameters per
    device are identical but the fill/drain bubble shrinks by ``rounds``:
    (D-1)/(M*rounds + D-1) vs (D-1)/(M + D-1).

    :param stacked_params: pytree with leading axes ``(D, rounds, ...)`` —
        chunk r of device d at ``[d, r]`` being virtual stage ``r*D + d``
        (:func:`circular_superstage` builds this from flat stacked layers).
    :param rounds: wraps around the device ring (1 = plain :func:`pipeline_apply`
        schedule with ring handoff).
    :returns: (batch, ...) output, microbatch-sharded over the stage axis.
    """
    num_devices = mesh.shape[axis]
    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(
            f"num_microbatches ({num_microbatches}) must evenly divide batch ({batch})"
        )
    if num_microbatches % num_devices:
        raise ValueError(
            f"the {axis!r} mesh axis size ({num_devices}) must evenly divide "
            f"num_microbatches ({num_microbatches})"
        )
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[:2] != (num_devices, rounds):
            raise ValueError(
                f"stacked_params leading axes {leaf.shape[:2]} must equal "
                f"(devices, rounds) = ({num_devices}, {rounds})"
            )

    x_mb = x.reshape((num_microbatches, batch // num_microbatches) + x.shape[1:])
    body_fn = jax.checkpoint(stage_fn) if remat else stage_fn
    params_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    body = functools.partial(
        _circular_local,
        stage_fn=body_fn,
        axis_name=axis,
        num_microbatches=num_microbatches,
        rounds=rounds,
    )
    out_mb = shard_map(
        body,
        mesh=mesh,
        in_specs=(params_spec, P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )(stacked_params, x_mb)
    return out_mb.reshape((batch,) + x.shape[1:])


def circular_superstage(
    layer_fn: Callable, stacked_layer_params: Any, num_devices: int, rounds: int
):
    """Arrange L stacked layers for :func:`pipeline_apply_circular`.

    Virtual stage v (= r*num_devices + d) owns layers ``[v*c, v*c + c)`` with
    ``c = L / (num_devices * rounds)``; like :func:`superstage`, each chunk body
    scans its layers sequentially. Returns ``(stage_fn, stage_params)`` with
    ``stage_params`` leaves shaped ``(num_devices, rounds, c, ...)``.
    """
    leaves = jax.tree_util.tree_leaves(stacked_layer_params)
    num_layers = leaves[0].shape[0]
    virtual = num_devices * rounds
    if num_layers % virtual:
        raise ValueError(
            f"num_layers ({num_layers}) must be divisible by devices*rounds ({virtual})"
        )
    per_chunk = num_layers // virtual

    def arrange(p):
        # layer order is (virtual stage, layer-in-chunk); virtual stage r*D + d
        # must land at [d, r], so split the leading axis as (rounds, D) and swap
        p = p.reshape((rounds, num_devices, per_chunk) + p.shape[1:])
        return jnp.swapaxes(p, 0, 1)

    stage_params = jax.tree_util.tree_map(arrange, stacked_layer_params)

    def stage_fn(params, h):
        def body(carry, layer_params):
            return layer_fn(layer_params, carry), None

        out, _ = lax.scan(body, h, params)
        return out

    return stage_fn, stage_params


def stage_sharding(mesh: Mesh, axis: str = STAGE_AXIS) -> NamedSharding:
    """Sharding for stacked per-stage parameters (leading stage axis)."""
    return NamedSharding(mesh, P(axis))


def superstage(layer_fn: Callable, stacked_layer_params: Any, num_stages: int):
    """Group L stacked layers into ``num_stages`` pipeline superstages.

    Deep models usually have more layers than pipeline devices (BERT-base: 12 layers
    on a 4-deep stage axis). This helper blocks consecutive layers onto one device —
    stage s owns layers ``[s*c, s*c + c)`` with ``c = L / num_stages`` — and returns
    ``(stage_fn, stage_params)`` ready for :func:`pipeline_apply`: the stage body
    scans its ``c`` layers sequentially (one fused superstage per tick, bubble
    fraction unchanged at ``(S-1)/(M+S-1)``).

    :param layer_fn: ``(layer_params, h) -> h`` for ONE layer.
    :param stacked_layer_params: pytree with leading axis L (all layers stacked).
    :returns: ``(stage_fn, stage_params)`` where stage_params carries a leading
        ``num_stages`` axis and stage_fn applies the local layer block via
        ``lax.scan`` (compiler-friendly; no per-layer retrace). Because the stage
        body contains a scan, the surrounding :func:`pipeline_apply` call must run
        under ``jax.jit`` (the normal train-step pattern).
    """
    leaves = jax.tree_util.tree_leaves(stacked_layer_params)
    num_layers = leaves[0].shape[0]
    if num_layers % num_stages:
        raise ValueError(
            f"num_layers ({num_layers}) must be divisible by num_stages ({num_stages})"
        )
    per_stage = num_layers // num_stages
    stage_params = jax.tree_util.tree_map(
        lambda p: p.reshape((num_stages, per_stage) + p.shape[1:]), stacked_layer_params
    )

    def stage_fn(params, h):
        def body(carry, layer_params):
            return layer_fn(layer_params, carry), None

        out, _ = lax.scan(body, h, params)
        return out

    return stage_fn, stage_params
