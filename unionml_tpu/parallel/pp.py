"""Pipeline parallelism: GPipe-style microbatching over a ``"stage"`` mesh axis.

Homogeneous-stage pipelining (the transformer-layers case): per-stage parameters are
stacked on a leading axis and sharded over ``stage``; microbatches flow device-to-device
via ``lax.ppermute`` (ICI neighbor exchange). The schedule runs
``num_microbatches + num_stages - 1`` ticks; at tick t, stage s computes microbatch
``t - s`` (the classic GPipe fill/steady/drain). Each device COMPUTES on one
microbatch per tick (compute O(batch/M) at a time); note that in this first version
the input and output buffers are replicated across stages for schedule simplicity, so
per-device BUFFER memory is O(batch) — stage-0-only feeding and per-tick collection
are the queued optimization (NEXT.md).

SURVEY.md §2 marks PP "future work" for the reference rebuild; here it lands as a
composable primitive (the dryrun exercises it alongside dp/fsdp/tp/sp).
"""

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

STAGE_AXIS = "stage"


def _pipeline_local(stage_params, x_mb, *, stage_fn, axis_name: str, num_microbatches: int):
    """Per-device schedule: consume at stage 0, compute own stage, pass rightward."""
    num_stages = lax.psum(1, axis_name)
    stage_index = lax.axis_index(axis_name)
    stage_params = jax.tree_util.tree_map(lambda p: p[0], stage_params)  # drop stage dim

    mb_shape = x_mb.shape[1:]
    outputs = jnp.zeros((num_microbatches,) + mb_shape, dtype=x_mb.dtype)
    carry = jnp.zeros(mb_shape, dtype=x_mb.dtype)
    perm = [(i, i + 1) for i in range(num_stages - 1)]

    def tick(t, state):
        outputs, carry = state
        feed_index = jnp.clip(t, 0, num_microbatches - 1)
        # stage 0 consumes a fresh microbatch; later stages consume the handoff
        h_in = jnp.where(stage_index == 0, x_mb[feed_index], carry)
        h_out = stage_fn(stage_params, h_in)
        # collect at the last stage once the pipeline has filled (t >= num_stages - 1)
        out_index = jnp.clip(t - (num_stages - 1), 0, num_microbatches - 1)
        is_output_tick = jnp.logical_and(stage_index == num_stages - 1, t >= num_stages - 1)
        outputs = jnp.where(
            is_output_tick,
            outputs.at[out_index].set(h_out),
            outputs,
        )
        carry = lax.ppermute(h_out, axis_name, perm)
        return outputs, carry

    total_ticks = num_microbatches + num_stages - 1
    outputs, _ = lax.fori_loop(0, total_ticks, tick, (outputs, carry))
    # only the last stage holds real outputs; psum replicates them across the axis
    outputs = jnp.where(stage_index == num_stages - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(outputs, axis_name)


def pipeline_apply(
    stage_fn: Callable,
    stacked_params: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    num_microbatches: int,
    axis: str = STAGE_AXIS,
) -> jax.Array:
    """Apply ``num_stages`` instances of ``stage_fn`` as a GPipe pipeline.

    :param stage_fn: ``(params, h) -> h`` with matching input/output shapes
        (homogeneous stages — the stacked-transformer-layers case).
    :param stacked_params: pytree whose leaves carry a leading ``num_stages`` axis,
        sharded over ``axis``.
    :param x: (batch, ...) input; ``num_microbatches`` must evenly divide ``batch``.
    :param num_microbatches: pipeline fill granularity; per-tick compute per stage
        scales with ``batch / num_microbatches`` while bubble fraction scales with
        ``(num_stages - 1) / (num_microbatches + num_stages - 1)``. Input/output
        buffers are currently replicated across stages (O(batch) buffer memory).
    :returns: (batch, ...) output, replicated over the stage axis.
    """
    num_stages = mesh.shape[axis]
    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(
            f"num_microbatches ({num_microbatches}) must evenly divide batch ({batch})"
        )
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != num_stages:
            raise ValueError(
                f"stacked_params leading axis ({leaf.shape[0]}) must equal the {axis!r} "
                f"mesh axis size ({num_stages})"
            )

    x_mb = x.reshape((num_microbatches, batch // num_microbatches) + x.shape[1:])

    params_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    body = functools.partial(
        _pipeline_local, stage_fn=stage_fn, axis_name=axis, num_microbatches=num_microbatches
    )
    out_mb = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(params_spec, P()),
        out_specs=P(),
        check_vma=False,
    )(stacked_params, x_mb)
    return out_mb.reshape((batch,) + x.shape[1:])


def stage_sharding(mesh: Mesh, axis: str = STAGE_AXIS) -> NamedSharding:
    """Sharding for stacked per-stage parameters (leading stage axis)."""
    return NamedSharding(mesh, P(axis))
