"""Ring attention: sequence/context parallelism over the mesh's sequence axis.

Long-context story (SURVEY.md §5 flags this as a designed extension point; here it is
implemented): Q/K/V arrive sequence-sharded over the ``"sequence"`` mesh axis; each
device keeps its Q shard resident and the K/V shards rotate around the ring via
``lax.ppermute`` (ICI neighbor exchange), one hop per step, while a flash-style online
softmax folds each visiting block into the local accumulator. Peak memory per device is
O(seq/N) and the N-1 permutes overlap naturally with the per-block matmuls under XLA's
scheduler — no materialized (seq x seq) score matrix anywhere.

Built with ``shard_map`` so it composes with the data/tensor axes of the same mesh
(batch stays sharded over "data", heads may be sharded over "tensor").
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax  # noqa: F401 - lax used throughout
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from unionml_tpu.parallel.mesh import DATA_AXIS, SEQUENCE_AXIS

from unionml_tpu.parallel._compat import shard_map

_NEG_INF = -1e30


def _local_block_attention(
    q, k_blk, v_blk, acc, row_max, row_sum, q_offset, k_offset, causal, sm_scale, kv_lens=None
):
    """Fold one visiting K/V block into the online-softmax accumulator.

    q: (b, h, Lq, d); k_blk/v_blk: (b, h, Lk, d); accumulators broadcast alike.
    Offsets are the global sequence positions of the local shards (for causal and
    padding masks). ``kv_lens`` is a (b,) per-batch valid length (right padding).
    """
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk, preferred_element_type=jnp.float32) * sm_scale
    k_pos = k_offset + lax.broadcasted_iota(jnp.int32, scores.shape, 3)
    if kv_lens is not None:
        scores = jnp.where(k_pos < kv_lens[:, None, None, None], scores, _NEG_INF)
    if causal:
        q_pos = q_offset + lax.broadcasted_iota(jnp.int32, scores.shape, 2)
        scores = jnp.where(q_pos >= k_pos, scores, _NEG_INF)

    block_max = jnp.max(scores, axis=-1, keepdims=True)
    new_max = jnp.maximum(row_max, block_max)
    correction = jnp.exp(row_max - new_max)
    # rows where every score seen so far is masked keep new_max == _NEG_INF;
    # exp(scores - new_max) would then be exp(0) == 1 and the accumulator would
    # absorb garbage V sums, so such rows must contribute zero probability mass
    # (they stay zero until a valid block arrives — fully-padded rows emit zeros).
    probs = jnp.where(new_max > _NEG_INF / 2, jnp.exp(scores - new_max), 0.0)
    acc = acc * correction + jnp.einsum(
        "bhqk,bhkd->bhqd", probs, v_blk.astype(jnp.float32), preferred_element_type=jnp.float32
    )
    row_sum = row_sum * correction + jnp.sum(probs, axis=-1, keepdims=True)
    return acc, new_max, row_sum


def _ring_attention_local(q, k, v, kv_lens, *, axis_name: str, causal: bool, sm_scale: float):
    """Per-device body: rotate K/V around the ring, folding blocks as they arrive."""
    axis_size = lax.psum(1, axis_name)
    my_index = lax.axis_index(axis_name)
    local_len = q.shape[-2]
    q32 = q.astype(jnp.float32)

    acc = jnp.zeros(q.shape[:-2] + (local_len, v.shape[-1]), dtype=jnp.float32)
    row_max = jnp.full(q.shape[:-2] + (local_len, 1), _NEG_INF, dtype=jnp.float32)
    row_sum = jnp.zeros(q.shape[:-2] + (local_len, 1), dtype=jnp.float32)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step_fn(carry, step):
        acc, row_max, row_sum, k_blk, v_blk = carry
        src_index = (my_index - step) % axis_size  # whose K/V block we hold this step
        acc, row_max, row_sum = _local_block_attention(
            q32,
            k_blk,
            v_blk,
            acc,
            row_max,
            row_sum,
            q_offset=my_index * local_len,
            k_offset=src_index * local_len,
            causal=causal,
            sm_scale=sm_scale,
            kv_lens=kv_lens,
        )
        # hand our current block to the right neighbor (ICI neighbor exchange)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (acc, row_max, row_sum, k_blk, v_blk), None

    (acc, row_max, row_sum, _, _), _ = lax.scan(
        step_fn, (acc, row_max, row_sum, k, v), jnp.arange(axis_size)
    )
    return (acc / jnp.maximum(row_sum, 1e-30)).astype(q.dtype)


def _sp_prologue(q, mesh, sm_scale, seq_axis, batch_axis, kv_lens):
    """Shared setup for the sequence-parallel entrypoints (ring + ulysses).

    Returns (softmax scale, activation spec, kv_lens spec, kv_lens-with-default).
    """
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    batch = batch_axis if batch_axis in mesh.axis_names else None
    spec = P(batch, None, seq_axis, None)
    lens_spec = P(batch)
    if kv_lens is None:
        kv_lens = jnp.full((q.shape[0],), q.shape[-2], dtype=jnp.int32)
    return scale, spec, lens_spec, kv_lens


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    kv_lens: Optional[jax.Array] = None,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    seq_axis: str = SEQUENCE_AXIS,
    batch_axis: str = DATA_AXIS,
) -> jax.Array:
    """Sequence-parallel attention over ``mesh``'s ``seq_axis``.

    Inputs are (batch, heads, seq, head_dim); ``seq`` must divide the sequence-axis
    size. Batch is sharded over ``batch_axis`` when present. ``kv_lens`` is a (batch,)
    valid-length vector (right-padding mask). The result carries ``q``'s sharding.
    """
    scale, spec, lens_spec, kv_lens = _sp_prologue(q, mesh, sm_scale, seq_axis, batch_axis, kv_lens)

    body = functools.partial(_ring_attention_local, axis_name=seq_axis, causal=causal, sm_scale=scale)
    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec, lens_spec),
        out_specs=spec,
        check_vma=False,
    )
    return mapped(q, k, v, kv_lens)


def sequence_sharding(mesh: Mesh, batch_axis: str = DATA_AXIS, seq_axis: str = SEQUENCE_AXIS) -> NamedSharding:
    """Sharding for (batch, heads, seq, head_dim) activations in the ring layout."""
    batch = batch_axis if batch_axis in mesh.axis_names else None
    return NamedSharding(mesh, P(batch, None, seq_axis, None))
