"""jax API compatibility shims for the parallelism engine.

``shard_map`` graduated from ``jax.experimental.shard_map`` (where its
replication-check flag is ``check_rep``) to ``jax.shard_map`` (flag renamed
``check_vma``). Every per-device program in this package routes through this
one wrapper so the version probe lives in exactly one place.
"""

from typing import Any, Callable

import jax

if hasattr(jax, "shard_map"):

    def shard_map(
        f: Callable, *, mesh: Any, in_specs: Any, out_specs: Any, check_vma: bool = True
    ) -> Callable:
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )

else:  # jax <= 0.4.x: experimental module, check_rep spelling
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(
        f: Callable, *, mesh: Any, in_specs: Any, out_specs: Any, check_vma: bool = True
    ) -> Callable:
        return _experimental_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
        )
