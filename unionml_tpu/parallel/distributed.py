"""Multi-host initialization: joining N hosts into one jax.distributed mesh.

The reference has no distributed backend at all (SURVEY.md §2: inter-task data moves via
blob store; intra-task is user code). Here multi-host is first-class: every backend
worker whose job spec declares ``host_count > 1`` calls
:func:`initialize_distributed` before any jax computation, after which
``jax.devices()`` spans the full pod slice and meshes built by
:mod:`unionml_tpu.parallel.mesh` cover all hosts (ICI within a slice, DCN across).
"""

import os
from typing import Optional

import jax

from unionml_tpu._logging import logger

_initialized = False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    strict: bool = False,
) -> bool:
    """Idempotently initialize ``jax.distributed``; returns True when initialized.

    On TPU VMs created as one slice, ``jax.distributed.initialize()`` auto-discovers
    everything from the TPU metadata server; explicit args (or the standard
    ``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID`` env vars)
    cover manual fleets. ``strict=True`` re-raises init failures — REQUIRED for
    multi-host jobs: a silent single-process fallback would make every host believe it
    is primary and run N uncoordinated copies of the job.
    """
    global _initialized
    if _initialized:
        return True

    coordinator_address = coordinator_address or os.getenv("JAX_COORDINATOR_ADDRESS")
    num_processes = num_processes if num_processes is not None else _int_env("JAX_NUM_PROCESSES")
    process_id = process_id if process_id is not None else _int_env("JAX_PROCESS_ID")

    try:
        if coordinator_address:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        else:
            jax.distributed.initialize()
        _initialized = True
        logger.info(
            "jax.distributed initialized: process %s/%s, %d local / %d global devices",
            jax.process_index(),
            jax.process_count(),
            jax.local_device_count(),
            jax.device_count(),
        )
        return True
    except (RuntimeError, ValueError) as exc:
        if strict:
            raise
        # single-process contexts (unit tests, one-host slices) are fine without init
        logger.info("jax.distributed not initialized (%s); continuing single-process.", exc)
        return False


def _int_env(name: str) -> Optional[int]:
    value = os.getenv(name)
    return int(value) if value is not None else None


def is_primary_host() -> bool:
    """True on the host responsible for writing outputs/checkpoints."""
    return jax.process_index() == 0
