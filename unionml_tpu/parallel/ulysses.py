"""Ulysses-style sequence parallelism: all-to-all head/sequence re-sharding.

The second long-context strategy next to :mod:`unionml_tpu.parallel.ring`: instead of
rotating K/V blocks, one ``all_to_all`` over the sequence axis re-shards activations
from sequence-sharded (each device: all heads, seq/N positions) to head-sharded (each
device: heads/N, full sequence). Attention then runs *unmodified* on full sequences for
the local head subset — any mask works, no online-softmax bookkeeping — and a second
all-to-all restores sequence sharding.

Trade-off vs ring: two all-to-alls of the full activations (ICI-friendly) but O(seq)
activation memory per device for its head subset, while ring keeps O(seq/N) memory and
overlaps its N-1 neighbor permutes with compute. The sequence-axis size must divide
the head count (e.g. 8 heads on a 4-way axis).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P  # noqa: F401 - P re-exported pattern

from unionml_tpu.parallel.mesh import DATA_AXIS, SEQUENCE_AXIS
from unionml_tpu.parallel.ring import _sp_prologue

from unionml_tpu.parallel._compat import shard_map


def _ulysses_local(q, k, v, kv_lens, *, axis_name: str, causal: bool, sm_scale: float):
    # deferred: unionml_tpu.ops pulls in pallas, which only the sp hot path needs
    from unionml_tpu.ops.attention import xla_attention

    # (b, h, s/N, d) -> (b, h/N, s, d): split heads across the axis, gather sequence
    to_heads = functools.partial(
        lax.all_to_all, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True
    )
    q, k, v = to_heads(q), to_heads(k), to_heads(v)
    seq_k = k.shape[-2]
    mask = (jnp.arange(seq_k)[None, :] < kv_lens[:, None])[:, None, None, :]
    out = xla_attention(q, k, v, mask=mask, causal=causal, sm_scale=sm_scale)
    # (b, h/N, s, d) -> (b, h, s/N, d)
    return lax.all_to_all(out, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    kv_lens: Optional[jax.Array] = None,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    seq_axis: str = SEQUENCE_AXIS,
    batch_axis: str = DATA_AXIS,
) -> jax.Array:
    """Sequence-parallel attention via head/sequence all-to-all re-sharding.

    Inputs are (batch, heads, seq, head_dim) sharded over ``seq_axis`` on the sequence
    dimension; ``heads`` must be divisible by the axis size. ``kv_lens`` is a (batch,)
    valid-length vector (right-padding mask). Output keeps the input sharding.
    """
    axis_size = mesh.shape[seq_axis]
    heads = q.shape[1]
    if heads % axis_size:
        raise ValueError(
            f"ulysses_attention requires heads ({heads}) divisible by the {seq_axis!r} "
            f"axis size ({axis_size}); use ring_attention otherwise."
        )
    scale, spec, lens_spec, kv_lens = _sp_prologue(q, mesh, sm_scale, seq_axis, batch_axis, kv_lens)

    body = functools.partial(_ulysses_local, axis_name=seq_axis, causal=causal, sm_scale=scale)
    return shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec, lens_spec), out_specs=spec, check_vma=False
    )(q, k, v, kv_lens)
