"""Device-mesh construction and sharding-spec plumbing.

This is the framework's "distributed communication backend" in the TPU idiom
(SURVEY.md §2 parallelism table): instead of an NCCL/MPI library, communication is
expressed as sharding annotations over a ``jax.sharding.Mesh``; XLA lowers them to ICI
collectives within a slice and DCN collectives across slices. Nothing here issues a
collective directly — the mesh + ``PartitionSpec`` layout IS the backend.

Axis convention (used across models/, parallel/, and the Dataset batch axis):

- ``"data"`` — batch sharding (DP)
- ``"fsdp"`` — parameter sharding along the data axis (ZeRO-style)
- ``"tensor"`` — tensor parallelism within attention/MLP blocks
- ``"sequence"`` — sequence/context parallelism (ring attention)
"""

from dataclasses import dataclass, field
from typing import Any, Dict, Mapping, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
TENSOR_AXIS = "tensor"
SEQUENCE_AXIS = "sequence"


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape: ordered mapping of axis name -> size.

    A size of ``-1`` means "all remaining devices" (at most one axis may use it).
    """

    axes: Tuple[Tuple[str, int], ...] = ((DATA_AXIS, -1),)

    @classmethod
    def from_dict(cls, axes: Mapping[str, int]) -> "MeshSpec":
        return cls(tuple(axes.items()))

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(name for name, _ in self.axes)

    def resolve_shape(self, n_devices: int) -> Tuple[int, ...]:
        sizes = [size for _, size in self.axes]
        wildcards = [i for i, s in enumerate(sizes) if s == -1]
        if len(wildcards) > 1:
            raise ValueError(f"At most one mesh axis may be -1; got {self.axes}")
        fixed = int(np.prod([s for s in sizes if s != -1])) if sizes else 1
        if wildcards:
            if n_devices % fixed != 0:
                raise ValueError(f"{n_devices} devices not divisible by fixed axes product {fixed}")
            sizes[wildcards[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(f"Mesh axes {self.axes} require {fixed} devices; found {n_devices}")
        return tuple(sizes)

    def build(self, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
        return make_mesh(dict(self.axes), devices=devices)


def make_mesh(
    axis_sizes: Optional[Mapping[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``Mesh`` over the given (default: all) devices.

    ``axis_sizes=None`` produces a 1-D data-parallel mesh over every device. Device
    ordering uses ``mesh_utils.create_device_mesh`` so ICI-adjacent chips land adjacent
    in the mesh (collectives ride ICI, not DCN).
    """
    devices = list(devices) if devices is not None else jax.devices()
    if axis_sizes is None:
        axis_sizes = {DATA_AXIS: len(devices)}
    spec = MeshSpec.from_dict(axis_sizes)
    shape = spec.resolve_shape(len(devices))
    try:
        device_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, AssertionError):
        # non-TPU or irregular topologies: plain reshape is still a valid mesh
        device_array = np.asarray(devices).reshape(shape)
    return Mesh(device_array, spec.axis_names)


def make_hybrid_mesh(
    ici_axes: Mapping[str, int],
    dcn_axes: Mapping[str, int],
) -> Mesh:
    """Multi-slice mesh: ``dcn_axes`` shard across slices (DCN), ``ici_axes`` within (ICI).

    Each logical axis may live in either (or both) domains; its total size is the
    product of its ICI and DCN extents. ``create_hybrid_device_mesh`` requires the two
    shape vectors to have equal rank, so both are expanded over the union of axis names
    with 1s where an axis is absent. Requires ``jax.distributed`` to be initialized (see
    :func:`unionml_tpu.parallel.distributed.initialize_distributed`).
    """
    names = list(dict.fromkeys([*dcn_axes, *ici_axes]))
    ici_shape = tuple(ici_axes.get(name, 1) for name in names)
    dcn_shape = tuple(dcn_axes.get(name, 1) for name in names)
    try:
        # TPU slices: slice_index is the DCN granule
        device_array = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=ici_shape,
            dcn_mesh_shape=dcn_shape,
        )
    except (ValueError, AssertionError):
        try:
            # multi-process CPU/GPU fleets: the PROCESS is the DCN granule, so the
            # dcn axes still land on real host boundaries (honest placement)
            device_array = mesh_utils.create_hybrid_device_mesh(
                mesh_shape=ici_shape,
                dcn_mesh_shape=dcn_shape,
                process_is_granule=True,
            )
        except (ValueError, AssertionError):
            if jax.process_count() > 1:
                # never silently reshape a real multi-host fleet: a wrong layout
                # would put "DCN" axes across arbitrary devices and hide the
                # placement bug the hybrid mesh exists to prevent
                raise
            # single-process emulation (unit tests): plain reshape with the same
            # logical shape; there is no host boundary to misplace
            total = tuple(i * d for i, d in zip(ici_shape, dcn_shape))
            device_array = np.asarray(jax.devices()[: int(np.prod(total))]).reshape(total)
    return Mesh(device_array, tuple(names))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (batch) dimension across ``axis``."""
    axes = tuple(a for a in (axis, FSDP_AXIS) if a in mesh.axis_names) if axis == DATA_AXIS else (axis,)
    present = tuple(a for a in axes if a in mesh.axis_names)
    return NamedSharding(mesh, PartitionSpec(present if len(present) > 1 else (present[0] if present else None)))


def batch_axis_size(mesh: Mesh, axis: str = DATA_AXIS) -> int:
    """Total device count the leading (batch) dim is sharded over under
    :func:`batch_sharding` — the data×fsdp product when both axes are present."""
    axes = tuple(a for a in (axis, FSDP_AXIS) if a in mesh.axis_names) if axis == DATA_AXIS else (axis,)
    size = 1
    for a in axes:
        if a in mesh.axis_names:
            size *= int(mesh.shape[a])
    return size


def wrapped_row_indices(n_rows: int, multiple: int):
    """Row indices that wrap-fill ``n_rows`` up to a multiple of ``multiple``.

    Returns ``None`` when already aligned. The fill repeats REAL rows (wrap-around)
    instead of fabricating zero rows, so a ragged batch rescued onto a mesh never
    trains or evaluates on fake data — a few examples are just slightly overweighted.
    Shared by every sharded-batch producer (``dp.batches``, ``dict_batches``, the
    prefetch path in ``fit``) so the rescue semantics cannot drift apart.
    """
    if multiple <= 1 or n_rows % multiple == 0:
        return None
    target = ((n_rows // multiple) + 1) * multiple
    return np.resize(np.arange(n_rows), target)


def shard_batch(batch: Any, mesh: Mesh, axis: str = DATA_AXIS) -> Any:
    """Lay a host batch (pytree) onto the mesh, sharded along the leading dim."""
    sharding = batch_sharding(mesh, axis)
    return jax.tree_util.tree_map(lambda leaf: jax.device_put(leaf, sharding), batch)


def logical_to_sharding(mesh: Mesh, *spec: Any) -> NamedSharding:
    """Convenience: ``PartitionSpec(*spec)`` bound to ``mesh``, dropping absent axes."""
    cleaned = tuple(s if (s is None or s in mesh.axis_names) else None for s in spec)
    return NamedSharding(mesh, PartitionSpec(*cleaned))


def named_sharding_tree(mesh: Mesh, spec_tree: Any) -> Any:
    """Bind a ``PartitionSpec`` pytree to ``mesh`` as a matching ``NamedSharding`` tree.

    The one place the spec->sharding tree_map lives: model ``param_shardings``
    tables produce spec trees, and every consumer (train-state layout in the
    driver, the sharded serving engine, the resident predictor) binds them to a
    concrete mesh through this helper.
    """
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        spec_tree,
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )
