"""Parallelism engine: meshes, data parallelism, sequence parallelism, multi-host.

See :mod:`unionml_tpu.parallel.mesh` for the axis conventions and the design stance:
communication is sharding annotations over a Mesh, lowered by XLA to ICI/DCN
collectives — the TPU-native replacement for an NCCL/MPI backend (SURVEY.md §2).
"""

from unionml_tpu.parallel.dp import batches, data_parallel_eval, data_parallel_step, pad_to_multiple
from unionml_tpu.parallel.ep import expert_sharding, moe_apply, moe_apply_capacity, moe_apply_topk
from unionml_tpu.parallel.pp import superstage, pipeline_apply, stage_sharding
from unionml_tpu.parallel.ring import ring_attention, sequence_sharding
from unionml_tpu.parallel.ulysses import ulysses_attention
from unionml_tpu.parallel.mesh import (
    DATA_AXIS,
    FSDP_AXIS,
    SEQUENCE_AXIS,
    TENSOR_AXIS,
    MeshSpec,
    batch_sharding,
    logical_to_sharding,
    make_hybrid_mesh,
    make_mesh,
    replicated,
    shard_batch,
)

__all__ = [
    "DATA_AXIS",
    "FSDP_AXIS",
    "SEQUENCE_AXIS",
    "TENSOR_AXIS",
    "MeshSpec",
    "batch_sharding",
    "batches",
    "data_parallel_eval",
    "data_parallel_step",
    "expert_sharding",
    "logical_to_sharding",
    "moe_apply",
    "moe_apply_capacity",
    "moe_apply_topk",
    "pipeline_apply",
    "superstage",
    "stage_sharding",
    "make_hybrid_mesh",
    "make_mesh",
    "pad_to_multiple",
    "replicated",
    "ring_attention",
    "sequence_sharding",
    "shard_batch",
    "ulysses_attention",
]
