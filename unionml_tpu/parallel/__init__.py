"""Parallelism engine: meshes, data parallelism, sequence parallelism, multi-host.

See :mod:`unionml_tpu.parallel.mesh` for the axis conventions and the design stance:
communication is sharding annotations over a Mesh, lowered by XLA to ICI/DCN
collectives — the TPU-native replacement for an NCCL/MPI backend (SURVEY.md §2).
"""

from unionml_tpu.parallel.dp import batches, data_parallel_eval, data_parallel_step, pad_to_multiple
from unionml_tpu.parallel.ep import (
    expert_sharding,
    moe_apply,
    moe_apply_a2a,
    moe_apply_capacity,
    moe_apply_topk,
)
from unionml_tpu.parallel.pp import (
    circular_superstage,
    pipeline_apply,
    pipeline_apply_circular,
    stage_sharding,
    superstage,
)
from unionml_tpu.parallel.ring import ring_attention, sequence_sharding
from unionml_tpu.parallel.ulysses import ulysses_attention
from unionml_tpu.parallel.mesh import (
    DATA_AXIS,
    FSDP_AXIS,
    SEQUENCE_AXIS,
    TENSOR_AXIS,
    MeshSpec,
    batch_sharding,
    logical_to_sharding,
    make_hybrid_mesh,
    make_mesh,
    named_sharding_tree,
    replicated,
    shard_batch,
)

__all__ = [
    "DATA_AXIS",
    "FSDP_AXIS",
    "SEQUENCE_AXIS",
    "TENSOR_AXIS",
    "MeshSpec",
    "batch_sharding",
    "batches",
    "data_parallel_eval",
    "data_parallel_step",
    "expert_sharding",
    "logical_to_sharding",
    "moe_apply",
    "moe_apply_a2a",
    "moe_apply_capacity",
    "moe_apply_topk",
    "circular_superstage",
    "pipeline_apply",
    "pipeline_apply_circular",
    "sp_attention",
    "superstage",
    "stage_sharding",
    "make_hybrid_mesh",
    "make_mesh",
    "named_sharding_tree",
    "pad_to_multiple",
    "replicated",
    "ring_attention",
    "sequence_sharding",
    "shard_batch",
    "ulysses_attention",
]


def sp_attention(q, k, v, mesh, impl: str, *, causal: bool = False, kv_lens=None):
    """Dispatch to a sequence-parallel attention impl ("ring" | "ulysses").

    The single place both model families route their long-context path through —
    one mesh check, one impl table (new strategies land here once).
    """
    if mesh is None:
        raise ValueError(f"attention_impl={impl!r} requires a sequence-parallel mesh (sp_mesh)")
    from unionml_tpu.parallel.ring import ring_attention
    from unionml_tpu.parallel.ulysses import ulysses_attention

    table = {"ring": ring_attention, "ulysses": ulysses_attention}
    try:
        fn = table[impl]
    except KeyError:
        raise ValueError(f"Unknown sequence-parallel impl {impl!r}; expected one of {sorted(table)}") from None
    return fn(q, k, v, mesh, causal=causal, kv_lens=kv_lens)
