"""Stage runtime: the choke point where user functions become executable pipeline stages.

Reference parity: ``unionml/utils.py:11-60`` (``inner_task``) wraps a closure into a
flytekit task with a synthesized keyword-only signature. Here the same choke point
produces a :class:`Stage` — a plain Python callable with a typed interface, resource
request, optional content-hash result caching, and a serializable address
``(module, variable, stage_name)`` for rehydration in backend workers.

TPU-native addition: :class:`TracedFunction` wraps user ``trainer``/``predictor``/
``evaluator`` callables as ``jax.jit``-compiled functions (the north-star requirement in
BASELINE.json). Policy ``"auto"`` traces when the inputs are jax-compatible pytrees and
falls back to eager execution for opaque model objects (sklearn estimators, torch
modules), so the same decorator surface serves both compiled-JAX and black-box trainers
(SURVEY.md §7 "opaque-trainer duality").
"""

import hashlib
import inspect
import os
import pickle
import time
from collections import OrderedDict
from functools import wraps
from pathlib import Path
from typing import Any, Callable, Dict, FrozenSet, Mapping, Optional, Sequence, Set, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from unionml_tpu._logging import logger
from unionml_tpu.defaults import DEFAULT_RESOURCES, Resources
from unionml_tpu.exceptions import StageError

_EMPTY = inspect.Parameter.empty

#: leaf types that can cross the trace boundary as dynamic (traced) values
_TRACEABLE_LEAVES = (jax.Array, np.ndarray, np.generic, float, int, bool, complex)
#: leaf types treated as static (compile-time constants) when auto-tracing
_STATIC_LEAVES = (str, bytes, type(None))
_TRACE_FAILED_KEYS_MAX = 128
# trace-time failures (data-dependent control flow, tracer leaks, concretization —
# all TypeError subclasses in jax.errors — plus AttributeError from numpy-only
# methods called on tracers) are eligible for eager fallback; runtime errors from
# compiled executables (JaxRuntimeError etc.) propagate instead
_TRACE_FAILURES = (TypeError, AttributeError, jax.errors.UnexpectedTracerError)


def is_jax_compatible(tree: Any) -> bool:
    """True when every leaf of ``tree`` can participate in a jax trace."""
    leaves = jax.tree_util.tree_leaves(tree)
    return all(isinstance(leaf, _TRACEABLE_LEAVES) for leaf in leaves)


def _scalarize(value: Any) -> Any:
    """Convert 0-d jax/numpy arrays to python scalars (for metrics dict parity)."""
    if isinstance(value, (jax.Array, np.ndarray)) and value.ndim == 0:
        return value.item()
    return value


class TracedFunction:
    """A user callable with a jit-compilation policy and eager fallback.

    :param fn: the user function.
    :param jit: ``True`` (always trace; errors surface), ``False`` (never trace), or
        ``"auto"`` (trace when inputs are jax-compatible; fall back to eager otherwise).
    :param static_argnames: kwarg names treated as compile-time constants.
    :param donate_argnums: positional args whose buffers XLA may reuse (HBM savings for
        the train-step pattern ``params = step(params, batch)``).
    :param in_shardings / out_shardings: optional sharding annotations forwarded to
        ``jax.jit`` — this is the pjit path used by the data-parallel engine.
    """

    def __init__(
        self,
        fn: Callable,
        *,
        jit: Union[bool, str] = "auto",
        static_argnames: Sequence[str] = (),
        donate_argnums: Sequence[int] = (),
        in_shardings: Any = None,
        out_shardings: Any = None,
    ):
        wraps(fn)(self)
        self._fn = fn
        self._policy = jit
        self._static_argnames = tuple(static_argnames)
        self._donate_argnums = tuple(donate_argnums)
        self._in_shardings = in_shardings
        self._out_shardings = out_shardings
        self._eager = jit is False
        self._compiled: Dict[FrozenSet[str], Callable] = {}
        self._trace_failed_keys: Set[Tuple] = set()

    @property
    def fn(self) -> Callable:
        return self._fn

    @property
    def uses_jit(self) -> bool:
        return not self._eager

    def _auto_static_names(self, kwargs: Mapping[str, Any]) -> Tuple[str, ...]:
        names = set(self._static_argnames)
        for key, value in kwargs.items():
            if isinstance(value, _STATIC_LEAVES) or not is_jax_compatible(value):
                names.add(key)
        return tuple(sorted(names))

    def _trace_key(self, static_names: Tuple[str, ...], args: Tuple, kwargs: Mapping[str, Any]) -> Tuple:
        """Identity of one call's trace: static names AND values, plus the abstract
        (shape/dtype/structure) signature of the traced arguments.

        jax.jit retraces per static value and per abstract signature, so a failure
        for one call must not disable compilation for calls jit would trace afresh
        (a different static value, or different array shapes/dtypes). Unhashable
        static values degrade to their type name. Only computed when a failure has
        already been recorded (or is being recorded) — zero hot-path cost otherwise.
        """
        vals = []
        for name in static_names:
            if name in kwargs:
                value = kwargs[name]
                try:
                    hash(value)
                except TypeError:
                    value = type(value).__name__
                vals.append((name, value))
        traced = {k: v for k, v in kwargs.items() if k not in static_names}
        leaves, treedef = jax.tree_util.tree_flatten((args, traced))
        abstract = tuple(
            (tuple(leaf.shape), str(leaf.dtype))
            if hasattr(leaf, "shape") and hasattr(leaf, "dtype")
            else type(leaf).__name__
            for leaf in leaves
        )
        return (static_names, tuple(vals), str(treedef), abstract)

    def _get_compiled(self, static_names: Tuple[str, ...]) -> Callable:
        key = frozenset(static_names)
        compiled = self._compiled.get(key)
        if compiled is None:
            # sharding kwargs stay conditional (passing None is not the same as
            # omitting them), but the donation is declared explicitly so static
            # analysis sees that this callable may consume its args' buffers
            jit_kwargs: Dict[str, Any] = {}
            if static_names:
                jit_kwargs["static_argnames"] = static_names
            if self._in_shardings is not None:
                jit_kwargs["in_shardings"] = self._in_shardings
            if self._out_shardings is not None:
                jit_kwargs["out_shardings"] = self._out_shardings
            compiled = jax.jit(self._fn, donate_argnums=self._donate_argnums, **jit_kwargs)
            self._compiled[key] = compiled
        return compiled

    def __call__(self, *args, **kwargs):
        if self._eager:
            return self._fn(*args, **kwargs)

        if self._policy == "auto" and not is_jax_compatible(args):
            # opaque model objects (sklearn/torch/keras) can never trace: permanent eager
            self._eager = True
            logger.debug("%s: inputs are not jax-compatible; running eagerly.", getattr(self._fn, "__name__", self._fn))
            return self._fn(*args, **kwargs)

        static_names = self._auto_static_names(kwargs)
        if self._trace_failed_keys and self._trace_key(static_names, args, kwargs) in self._trace_failed_keys:
            # this exact call signature failed to trace before; run it eagerly
            # without downgrading other (traceable) call shapes on the instance
            return self._fn(*args, **kwargs)
        try:
            return self._get_compiled(static_names)(*args, **kwargs)
        except Exception as exc:
            if self._policy == "auto" and isinstance(exc, _TRACE_FAILURES):
                if len(self._trace_failed_keys) >= _TRACE_FAILED_KEYS_MAX:
                    # bound the blacklist: per-request static values (ids, dates)
                    # would otherwise grow it for the process lifetime; clearing
                    # just means an occasional re-attempted (failing) trace
                    self._trace_failed_keys.clear()
                # graftlint: disable=use-after-donate -- reads only shape/dtype metadata, which survives donation (and trace failures raise before any donation executes)
                self._trace_failed_keys.add(self._trace_key(static_names, args, kwargs))
                logger.info(
                    "%s: jit tracing failed (%s: %s); falling back to eager execution for this call signature.",
                    getattr(self._fn, "__name__", self._fn),
                    type(exc).__name__,
                    exc,
                )
                # graftlint: disable=use-after-donate -- safe ONLY because every _TRACE_FAILURES type raises at trace time, before the executable runs: donation consumes buffers at execution, so the args are intact here; execution-time failures re-raise below. Widening _TRACE_FAILURES to any runtime error type would make this a real use-after-donate.
                return self._fn(*args, **kwargs)
            if self._policy == "auto":
                # runtime failure of an already-compiled executable (or an error the
                # user fn raised): propagate — masking it behind a permanent eager
                # downgrade would hide real failures and lose the compiled hot path
                raise
            raise StageError(f"jit compilation of {self._fn} failed") from exc


def _default_cache_root() -> Path:
    return Path(os.getenv("UNIONML_TPU_HOME", Path.home() / ".unionml-tpu")) / "cache"


def _fingerprint(payload: Any) -> str:
    try:
        raw = pickle.dumps(payload)
    except Exception:  # graftlint: disable=swallowed-exception -- unpicklable payloads get an empty fingerprint, which disables caching for them by design
        return ""
    return hashlib.sha256(raw).hexdigest()


class Stage:
    """An executable pipeline stage with a typed keyword-only interface.

    Stages are the unit the workflow engine wires together and the unit the execution
    backend ships to workers. A stage's address is ``(app module, tracked variable,
    stage name)`` — see :mod:`unionml_tpu.tracker`.
    """

    def __init__(
        self,
        fn: Callable,
        *,
        name: str,
        owner: Any = None,
        inputs: "OrderedDict[str, inspect.Parameter]",
        output_annotation: Any = _EMPTY,
        requests: Resources = DEFAULT_RESOURCES,
        limits: Resources = DEFAULT_RESOURCES,
        cache: bool = False,
        cache_version: str = "0",
        **extra_options: Any,
    ):
        self._fn = fn
        self.name = name
        self.owner = owner
        self.inputs: "OrderedDict[str, inspect.Parameter]" = inputs
        self.output_annotation = output_annotation
        self.requests = requests
        self.limits = limits
        self.cache = cache
        self.cache_version = cache_version
        self.options = extra_options
        self.last_duration: Optional[float] = None

    @property
    def python_interface(self) -> "StageInterface":
        return StageInterface(
            inputs=OrderedDict((k, p.annotation) for k, p in self.inputs.items()),
            outputs=_output_mapping(self.output_annotation),
        )

    def _cache_path(self, digest: str) -> Path:
        safe_name = self.name.replace("/", "_")
        return _default_cache_root() / safe_name / self.cache_version / f"{digest}.pkl"

    def __call__(self, **kwargs: Any) -> Any:
        unknown = set(kwargs) - set(self.inputs)
        if unknown:
            raise StageError(f"Stage {self.name} received unknown arguments: {sorted(unknown)}")

        digest = ""
        if self.cache:
            digest = _fingerprint((self.name, self.cache_version, sorted(kwargs.items(), key=lambda kv: kv[0])))
            if digest:
                path = self._cache_path(digest)
                if path.exists():
                    logger.debug("Stage %s: cache hit (%s)", self.name, digest[:12])
                    with path.open("rb") as f:
                        return pickle.load(f)

        start = time.perf_counter()
        result = self._fn(**kwargs)
        self.last_duration = time.perf_counter() - start
        logger.debug("Stage %s ran in %.4fs", self.name, self.last_duration)

        if self.cache and digest:
            path = self._cache_path(digest)
            path.parent.mkdir(parents=True, exist_ok=True)
            try:
                with path.open("wb") as f:
                    pickle.dump(result, f)
            except Exception as exc:  # unpicklable results simply skip the cache
                logger.debug("Stage %s: result not cacheable (%s)", self.name, exc)
        return result

    def __repr__(self) -> str:
        return f"Stage(name={self.name!r}, inputs={list(self.inputs)}, cache={self.cache})"


class StageInterface:
    """Typed input/output view of a stage (flytekit ``python_interface`` analogue)."""

    def __init__(self, inputs: "OrderedDict[str, Any]", outputs: "OrderedDict[str, Any]"):
        self.inputs = inputs
        self.outputs = outputs


def _output_mapping(annotation: Any) -> "OrderedDict[str, Any]":
    """Expose NamedTuple outputs as named fields, everything else as a single output ``o0``."""
    fields = getattr(annotation, "_fields", None)
    if fields is not None and hasattr(annotation, "__annotations__"):
        return OrderedDict((f, annotation.__annotations__.get(f, Any)) for f in fields)
    return OrderedDict([("o0", annotation)])


def stage(
    fn: Optional[Callable] = None,
    *,
    unionml_obj: Any,
    input_parameters: Optional[Mapping[str, inspect.Parameter]] = None,
    return_annotation: Any = _EMPTY,
    **stage_kwargs: Any,
) -> Union[Callable, Stage]:
    """Build a :class:`Stage` from a closure defined inside Dataset/Model.

    The synthesized interface is keyword-only, named ``{obj.name}.{fn.__name__}`` —
    reference parity with ``inner_task`` (``unionml/utils.py:40-60``).
    """
    if fn is None:
        def _bind(inner_fn: Callable) -> Stage:
            return stage(
                inner_fn,
                unionml_obj=unionml_obj,
                input_parameters=input_parameters,
                return_annotation=return_annotation,
                **stage_kwargs,
            )
        return _bind

    fn_sig = inspect.signature(fn)
    params = input_parameters if input_parameters is not None else fn_sig.parameters
    interface = OrderedDict(
        (name, p.replace(kind=inspect.Parameter.KEYWORD_ONLY)) for name, p in params.items()
    )
    output = fn_sig.return_annotation if return_annotation is _EMPTY else return_annotation

    known = {"requests", "limits", "cache", "cache_version"}
    core = {k: v for k, v in stage_kwargs.items() if k in known}
    extra = {k: v for k, v in stage_kwargs.items() if k not in known}
    built = Stage(
        fn,
        name=f"{unionml_obj.name}.{fn.__name__}",
        owner=unionml_obj,
        inputs=interface,
        output_annotation=output,
        **core,
        **extra,
    )
    return built
