"""Deployment client helpers: app versioning, deployment, and artifact lineage queries.

Reference parity: ``unionml/remote.py`` — ``get_app_version`` (git sha + dirty-tree
check, ``remote.py:45-59``), ``get_model`` app import (``remote.py:30-35``), workflow
deployment (``remote.py:125-161``), and the lineage queries (``remote.py:200-350``).

TPU-native deltas: no docker build/push — deployment records the app's rehydration
address + TPU pod-slice resources in the backend's app registry; "patch" deployment
(code-only fast registration) maps to re-registering the same app version with a
``-patch<uuid>`` suffix without any image work.
"""

import subprocess
import sys
import uuid
from pathlib import Path
from typing import TYPE_CHECKING, Any, List, Optional

from unionml_tpu._logging import logger
from unionml_tpu.exceptions import BackendError, ModelArtifactNotFound, VersionFetchError

if TYPE_CHECKING:
    from unionml_tpu.backend import Execution, LocalBackend
    from unionml_tpu.model import Model, ModelArtifact


def get_model(app: str, reload: bool = False) -> "Model":
    """Import ``module:variable`` and return the Model (``remote.py:30-35``)."""
    import importlib

    module_name, model_var = app.split(":")
    sys.path.insert(0, str(Path.cwd()))
    try:
        module = importlib.import_module(module_name)
        if reload:
            importlib.reload(module)
        return getattr(module, model_var)
    finally:
        sys.path.pop(0)


def get_app_version(allow_uncommitted: bool = False) -> str:
    """Derive the app version from the git HEAD sha (``remote.py:45-59``)."""
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True, check=True
        ).stdout.strip()
    except (subprocess.CalledProcessError, FileNotFoundError) as exc:
        raise VersionFetchError(
            "Could not determine app version from git; run inside a git repository or pass app_version explicitly."
        ) from exc

    dirty = bool(
        subprocess.run(["git", "status", "--porcelain"], capture_output=True, text=True).stdout.strip()
    )
    if dirty:
        if not allow_uncommitted:
            raise VersionFetchError(
                "Version check failed: the repository has uncommitted changes. Commit them or pass "
                "allow_uncommitted=True."
            )
        return f"{sha[:12]}-dirty"
    return sha[:12]


def deploy_app(
    model: "Model",
    backend: "LocalBackend",
    app_version: Optional[str] = None,
    allow_uncommitted: bool = False,
    patch: bool = False,
    schedule: bool = True,
) -> str:
    """Register the app's three workflows (+ schedules) with the backend.

    Mirrors ``Model.remote_deploy`` (``unionml/model.py:983-1083``) minus docker: there
    is no image build — the job spec ships the module address and TPU resources.
    """
    explicit_version = app_version is not None
    app_version = app_version or get_app_version(allow_uncommitted=allow_uncommitted or patch)
    if patch and not explicit_version:
        app_version = f"{app_version}-patch{uuid.uuid4().hex[:7]}"

    backend.create_project(getattr(backend, "default_project", None))
    logger.info("Deploying app version %s", app_version)

    for workflow_name in (
        model.train_workflow_name,
        model.predict_workflow_name,
        model.predict_from_features_workflow_name,
    ):
        backend.deploy_workflow(model, workflow_name, app_version=app_version, patch=patch)

    if schedule:
        for sched in [*model.training_schedules, *model.prediction_schedules]:
            backend.deploy_schedule(model, sched, app_version=app_version)
            if sched.activate_on_deploy:
                backend.activate_schedule(model, sched, app_version=app_version)

    return app_version


def get_model_execution(
    model: "Model",
    app_version: Optional[str] = None,
    model_version: Optional[str] = None,
) -> "Execution":
    """Latest successful training execution, or a specific one by id (``remote.py:200-269``)."""
    backend = model._remote
    if model_version and model_version != "latest":
        return backend.get_execution(model_version)
    executions = backend.list_executions(
        workflow_name=model.train_workflow_name, app_version=app_version, only_successful=True, limit=1
    )
    if not executions:
        raise ModelArtifactNotFound(
            f"No successful training executions found for {model.train_workflow_name}"
            + (f" at app version {app_version}" if app_version else "")
        )
    return executions[0]


def get_model_artifact(
    model: "Model",
    app_version: Optional[str] = None,
    model_version: Optional[str] = None,
) -> "ModelArtifact":
    """Fetch a trained model artifact from backend lineage (``remote.py:272-280``)."""
    from unionml_tpu.backend import wire_decode_value
    from unionml_tpu.model import ModelArtifact

    execution = get_model_execution(model, app_version=app_version, model_version=model_version)
    try:
        outputs = execution.outputs
    except BackendError as exc:
        raise ModelArtifactNotFound(str(exc)) from exc
    model_object = wire_decode_value(outputs["model_object"], model)
    return ModelArtifact(model_object, outputs.get("hyperparameters"), outputs.get("metrics"))


def list_model_versions(model: "Model", app_version: Optional[str] = None, limit: int = 10) -> List[str]:
    """Training execution ids, newest first (``remote.py:283-305``)."""
    backend = model._remote
    return [
        e.id
        for e in backend.list_executions(
            workflow_name=model.train_workflow_name, app_version=app_version, only_successful=True, limit=limit
        )
    ]


def list_prediction_ids(model: "Model", app_version: Optional[str] = None, limit: int = 10) -> List[str]:
    """Batch-prediction execution ids, newest first (``remote.py:308-330``)."""
    backend = model._remote
    ids: List[str] = []
    for workflow_name in (model.predict_workflow_name, model.predict_from_features_workflow_name):
        ids.extend(
            e.id
            for e in backend.list_executions(
                workflow_name=workflow_name, app_version=app_version, only_successful=True, limit=limit
            )
        )
    return ids[:limit]


def get_scheduled_runs(
    backend: "LocalBackend", schedule_name: str, app_version: Optional[str] = None, limit: int = 5
) -> List["Execution"]:
    """``remote.py:333-350`` analogue."""
    return backend.list_scheduled_runs(schedule_name, app_version=app_version, limit=limit)
