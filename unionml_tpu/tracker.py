"""Instance tracking: resolve Dataset/Model objects back to importable module variables.

Why this exists: when a stage runs in a *different process* (a backend worker, a serving
replica, or one host of a multi-host TPU slice), the worker only receives a string triple
``(module, variable, stage)``. It must re-import the user's app module and find the same
``Dataset``/``Model`` object to rebuild the stage. This mirrors the reference's tracker
(``unionml/tracker.py:21-99``, built on flytekit's tracker) but is self-contained.

The ``__main__`` edge case: if the app module was executed as a script, its module name is
``__main__`` which is not importable elsewhere; we reconstruct an importable dotted name
from the file path relative to the current working directory (``tracker.py:23-34`` in the
reference does the same).
"""

import importlib
import importlib.util
import inspect
import sys
from pathlib import Path
from typing import Any, Optional, Tuple

from unionml_tpu._logging import logger
from unionml_tpu.exceptions import TrackingError


def import_module_from_file(module_name: str, file: str) -> Any:
    """Import a module object given its dotted name and source file path."""
    existing = sys.modules.get(module_name)
    if existing is not None:
        return existing
    try:
        spec = importlib.util.spec_from_file_location(module_name, file)
        assert spec is not None and spec.loader is not None
        module = importlib.util.module_from_spec(spec)
        sys.modules[module_name] = module
        spec.loader.exec_module(module)
        return module
    except Exception as exc:
        sys.modules.pop(module_name, None)
        raise TrackingError(f"Module {module_name} could not be loaded from {file}") from exc


def _module_name_from_path(file: str) -> Optional[str]:
    """Derive an importable dotted module name for a script executed as __main__."""
    path = Path(file).resolve()
    cwd = Path.cwd().resolve()
    try:
        rel = path.relative_to(cwd)
    except ValueError:
        return None
    parts = rel.with_suffix("").parts
    if not parts:
        return None
    return ".".join(parts)


def _caller_module() -> Tuple[Optional[str], Optional[str]]:
    """Walk up the interpreter stack to the module-level frame that created the instance."""
    frame = inspect.currentframe()
    while frame is not None:
        globals_ = frame.f_globals
        if frame.f_code.co_name == "<module>" and "__name__" in globals_:
            name = globals_["__name__"]
            file = globals_.get("__file__")
            if name == "__main__":
                if file is None:
                    return None, None
                resolved = _module_name_from_path(file)
                return resolved, file
            return name, file
        frame = frame.f_back
    return None, None


class InstanceTrackingMeta(type):
    """Metaclass stamping each new instance with the module it was defined in."""

    def __call__(cls, *args, **kwargs):
        instance = super().__call__(*args, **kwargs)
        mod_name, mod_file = _caller_module()
        instance._instantiated_in = mod_name
        instance._module_file = mod_file
        return instance


class TrackedInstance(metaclass=InstanceTrackingMeta):
    """Base class for objects that must be re-importable by (module, variable) name."""

    def __init__(self, *args, **kwargs):
        self._instantiated_in: Optional[str] = None
        self._module_file: Optional[str] = None
        self._lhs: Optional[str] = None
        super().__init__(*args, **kwargs)

    @property
    def instantiated_in(self) -> Optional[str]:
        return self._instantiated_in

    def find_lhs(self) -> str:
        """Find the module-level variable name this instance is bound to.

        Reference parity: ``unionml/tracker.py:78-99`` — scan the defining module for a
        variable holding an object of the same type and name.
        """
        if self._lhs is not None:
            return self._lhs

        if self._instantiated_in is None:
            raise TrackingError(f"Instance {self!r} was not created at module scope; cannot track it.")

        try:
            module = sys.modules.get(self._instantiated_in) or importlib.import_module(self._instantiated_in)
        except ImportError:
            if self._module_file is None:
                raise TrackingError(f"Cannot import module {self._instantiated_in} and no source file is known.")
            module = import_module_from_file(self._instantiated_in, self._module_file)

        for varname in dir(module):
            try:
                candidate = getattr(module, varname)
            except AttributeError:  # pragma: no cover - defensive
                continue
            if candidate is self:
                self._lhs = varname
                return varname
        # fall back to matching by type + name for re-imported module copies
        for varname in dir(module):
            try:
                candidate = getattr(module, varname)
            except AttributeError:  # pragma: no cover - defensive
                continue
            # a re-imported module copy holds a distinct-but-equivalent class object, so
            # compare by qualified type name rather than identity
            if (
                type(candidate).__qualname__ == type(self).__qualname__
                and isinstance(candidate, TrackedInstance)
                and getattr(candidate, "name", None) == getattr(self, "name", None)
                and candidate.__dict__.get("_instantiated_in") == self._instantiated_in
            ):
                self._lhs = varname
                return varname

        logger.error("Could not find variable for %r in module %s", self, self._instantiated_in)
        raise TrackingError(f"Could not find a module-level variable for {self!r} in {self._instantiated_in}")


def load_tracked_instance(module_name: str, variable: str, module_file: Optional[str] = None) -> Any:
    """Worker-side rehydration: import the app module and return the tracked object.

    This is the process/machine boundary crossing used by the backend worker entrypoint
    (reference: ``unionml/task_resolver.py:16-31``).
    """
    try:
        module = sys.modules.get(module_name) or importlib.import_module(module_name)
    except ImportError:
        if module_file is None:
            raise
        module = import_module_from_file(module_name, module_file)
    try:
        return getattr(module, variable)
    except AttributeError as exc:
        raise TrackingError(f"Module {module_name} has no attribute {variable!r}") from exc
