"""BERT encoder family (flax) — the flagship model for the BERT-base fine-tune target.

Built TPU-first rather than ported: bfloat16 compute with f32 params/logits, the
framework's flash-attention kernel (:mod:`unionml_tpu.ops.attention`) behind every
layer, optional remat (``jax.checkpoint``) on encoder layers to trade FLOPs for HBM,
and a logical-axis sharding map (``param_shardings``) covering data/FSDP/tensor
parallelism so the same module runs single-chip or pjit-sharded over a mesh.

HF-compatible: ``import_hf_weights`` maps a ``transformers`` BERT state dict onto this
module's parameter tree (validated numerically against torch in tests).

Reference context: the reference has no model zoo at all — its BERT story is "user
brings a HF Trainer inside @model.trainer" (``templates/quickdraw``-style); here the
framework owns the model + train step so the TPU path is compiled end-to-end
(BASELINE.json north star).
"""

import dataclasses
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from unionml_tpu.ops.attention import attention
from unionml_tpu.parallel.mesh import DATA_AXIS, FSDP_AXIS, TENSOR_AXIS


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    num_labels: int = 2
    dtype: Any = jnp.bfloat16
    #: "auto" | "xla" | "pallas" | "ring" | "ulysses" — the last two are the
    #: sequence-parallel long-context paths and require ``sp_mesh``
    attention_impl: str = "auto"
    #: mesh carrying a "sequence" axis for ring/ulysses attention
    sp_mesh: Any = None
    remat: bool = False
    #: tanh-approximate GELU trades exact erf (VPU-expensive) for the cheaper tanh
    #: polynomial — numerically within ~1e-3 of exact, a candidate MFU lever whose
    #: value is measured on hardware by bench_mfu.py before changing any default
    gelu_approximate: bool = False

    @classmethod
    def base(cls, **overrides) -> "BertConfig":
        return cls(**overrides)

    @classmethod
    def tiny(cls, **overrides) -> "BertConfig":
        """A 2-layer config for tests and multi-chip dry runs."""
        defaults = dict(
            vocab_size=1024,
            hidden_size=128,
            num_layers=2,
            num_heads=4,
            intermediate_size=256,
            max_position_embeddings=128,
        )
        defaults.update(overrides)
        return cls(**defaults)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


class BertSelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, hidden, attn_inputs, deterministic: bool):
        cfg = self.config
        dense = lambda name: nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name=name)
        q = dense("query")(hidden)
        k = dense("key")(hidden)
        v = dense("value")(hidden)

        batch, seq, _ = hidden.shape
        kv_lens, dense_mask = attn_inputs
        split = lambda x: x.reshape(batch, seq, cfg.num_heads, cfg.head_dim).transpose(0, 2, 1, 3)
        if cfg.attention_impl in ("ring", "ulysses"):
            # sequence-parallel long-context path: activations shard over the mesh's
            # "sequence" axis; padding arrives as per-batch kv_lens (right padding)
            from unionml_tpu.parallel import sp_attention

            context = sp_attention(
                split(q), split(k), split(v), cfg.sp_mesh, cfg.attention_impl, kv_lens=kv_lens
            )
        else:
            context = attention(
                split(q), split(k), split(v), mask=dense_mask, kv_lens=kv_lens, impl=cfg.attention_impl
            )
        context = context.transpose(0, 2, 1, 3).reshape(batch, seq, cfg.hidden_size)

        out = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="output")(context)
        out = nn.Dropout(cfg.hidden_dropout)(out, deterministic=deterministic)
        return nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, name="output_norm")(
            out + hidden
        )


class BertMlp(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, hidden, deterministic: bool):
        cfg = self.config
        up = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype, name="intermediate")(hidden)
        up = nn.gelu(up, approximate=cfg.gelu_approximate)
        down = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="output")(up)
        down = nn.Dropout(cfg.hidden_dropout)(down, deterministic=deterministic)
        return nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, name="output_norm")(
            down + hidden
        )


class BertLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, hidden, attn_inputs, deterministic: bool):
        hidden = BertSelfAttention(self.config, name="attention")(hidden, attn_inputs, deterministic)
        return BertMlp(self.config, name="mlp")(hidden, deterministic)


class BertEncoder(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, hidden, attn_inputs, deterministic: bool):
        layer_cls = BertLayer
        if self.config.remat:
            layer_cls = nn.remat(BertLayer, static_argnums=(3,))
        for i in range(self.config.num_layers):
            hidden = layer_cls(self.config, name=f"layer_{i}")(hidden, attn_inputs, deterministic)
        return hidden


class BertModel(nn.Module):
    """Embeddings + encoder + pooler (tanh over [CLS])."""

    config: BertConfig

    @nn.compact
    def __call__(
        self,
        input_ids,
        attention_mask=None,
        token_type_ids=None,
        deterministic: bool = True,
    ):
        cfg = self.config
        batch, seq = input_ids.shape
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        # the flash kernel consumes per-batch valid lengths, which is exact only for
        # contiguous right-padding (the HF default); whenever the XLA impl is what
        # actually runs (explicitly or via "auto" off-TPU) it gets the full dense mask
        # so left-padded / arbitrary masks stay exact
        kv_lens = None
        dense_mask = None
        if attention_mask is not None:
            resolved_impl = cfg.attention_impl
            if resolved_impl == "auto":
                from unionml_tpu.ops.attention import on_tpu

                resolved_impl = "pallas" if on_tpu() else "xla"
            if resolved_impl == "xla":
                dense_mask = attention_mask[:, None, None, :].astype(bool)
            else:
                # pallas / ring / ulysses consume per-batch lengths (right padding);
                # the sp entrypoints default missing kv_lens to full length themselves
                kv_lens = jnp.sum(attention_mask.astype(jnp.int32), axis=-1)

        word = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, name="word_embeddings")(
            input_ids
        )
        position = nn.Embed(
            cfg.max_position_embeddings, cfg.hidden_size, dtype=cfg.dtype, name="position_embeddings"
        )(jnp.arange(seq)[None, :])
        token_type = nn.Embed(
            cfg.type_vocab_size, cfg.hidden_size, dtype=cfg.dtype, name="token_type_embeddings"
        )(token_type_ids)

        hidden = word + position + token_type
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, name="embeddings_norm")(hidden)
        hidden = nn.Dropout(cfg.hidden_dropout)(hidden, deterministic=deterministic)

        hidden = BertEncoder(cfg, name="encoder")(hidden, (kv_lens, dense_mask), deterministic)

        pooled = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="pooler")(hidden[:, 0])
        pooled = jnp.tanh(pooled)
        return hidden, pooled


class BertForSequenceClassification(nn.Module):
    """BERT + classification head — the fine-tune target model."""

    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None, deterministic: bool = True):
        _, pooled = BertModel(self.config, name="bert")(
            input_ids, attention_mask, token_type_ids, deterministic
        )
        pooled = nn.Dropout(self.config.hidden_dropout)(pooled, deterministic=deterministic)
        # classification logits in f32: cheap, and keeps the loss numerically exact
        return nn.Dense(self.config.num_labels, dtype=jnp.float32, name="classifier")(pooled)


# ---------------------------------------------------------------------- shardings

def param_shardings(params: Any, mesh_axis_names: Tuple[str, ...] = (DATA_AXIS, TENSOR_AXIS)) -> Any:
    """PartitionSpec tree for the BERT parameter pytree.

    Layout (the standard Megatron-style split expressed as jax shardings):

    - attention q/k/v kernels: shard output dim (heads) over ``tensor``
    - attention output kernel: shard input dim over ``tensor``
    - MLP up-projection: shard output dim over ``tensor``; down-projection: input dim
    - embeddings: shard vocab dim over ``tensor``
    - everything else replicated (or FSDP-sharded over ``fsdp`` when that axis exists)

    XLA inserts the matching all-reduces over ICI; nothing else is needed.
    """
    from jax.sharding import PartitionSpec as P

    has_tensor = TENSOR_AXIS in mesh_axis_names
    has_fsdp = FSDP_AXIS in mesh_axis_names
    tensor = TENSOR_AXIS if has_tensor else None
    fsdp = FSDP_AXIS if has_fsdp else None

    def spec_for(path: Tuple[str, ...], leaf) -> P:
        path_str = "/".join(str(p) for p in path)
        ndim = getattr(leaf, "ndim", 0)
        if ndim < 2:
            return P()
        if "embeddings" in path_str and "kernel" not in path_str:
            return P(tensor, None)
        if any(n in path_str for n in ("query", "key", "value", "intermediate")) and path_str.endswith("kernel"):
            return P(fsdp, tensor)
        if ("attention/output" in path_str or "mlp/output" in path_str) and path_str.endswith("kernel"):
            return P(tensor, fsdp)
        if path_str.endswith("kernel"):
            return P(fsdp, None)
        return P()

    from unionml_tpu.models._sharding import shard_by_rules

    return shard_by_rules(params, spec_for)


# ---------------------------------------------------------------------- HF import

def import_hf_weights(hf_state_dict: Dict[str, Any], config: BertConfig) -> Dict[str, Any]:
    """Map a HuggingFace BERT state dict (torch tensors or numpy) onto this module.

    Accepts ``BertModel`` or ``BertForSequenceClassification`` state dicts; torch
    ``Linear`` weights are (out, in) and transpose to flax (in, out) kernels.
    """

    def t(name: str) -> np.ndarray:
        value = hf_state_dict[name]
        if hasattr(value, "detach"):
            value = value.detach().cpu().numpy()
        return np.asarray(value)

    def linear(prefix: str) -> Dict[str, np.ndarray]:
        return {"kernel": t(f"{prefix}.weight").T, "bias": t(f"{prefix}.bias")}

    def norm(prefix: str) -> Dict[str, np.ndarray]:
        return {"scale": t(f"{prefix}.weight"), "bias": t(f"{prefix}.bias")}

    prefix = "bert." if any(key.startswith("bert.") for key in hf_state_dict) else ""
    bert: Dict[str, Any] = {
        "word_embeddings": {"embedding": t(f"{prefix}embeddings.word_embeddings.weight")},
        "position_embeddings": {"embedding": t(f"{prefix}embeddings.position_embeddings.weight")},
        "token_type_embeddings": {"embedding": t(f"{prefix}embeddings.token_type_embeddings.weight")},
        "embeddings_norm": norm(f"{prefix}embeddings.LayerNorm"),
        "pooler": linear(f"{prefix}pooler.dense"),
        "encoder": {},
    }
    for i in range(config.num_layers):
        hf_layer = f"{prefix}encoder.layer.{i}"
        bert["encoder"][f"layer_{i}"] = {
            "attention": {
                "query": linear(f"{hf_layer}.attention.self.query"),
                "key": linear(f"{hf_layer}.attention.self.key"),
                "value": linear(f"{hf_layer}.attention.self.value"),
                "output": linear(f"{hf_layer}.attention.output.dense"),
                "output_norm": norm(f"{hf_layer}.attention.output.LayerNorm"),
            },
            "mlp": {
                "intermediate": linear(f"{hf_layer}.intermediate.dense"),
                "output": linear(f"{hf_layer}.output.dense"),
                "output_norm": norm(f"{hf_layer}.output.LayerNorm"),
            },
        }

    params: Dict[str, Any] = {"bert": bert}
    if "classifier.weight" in hf_state_dict:
        params["classifier"] = linear("classifier")
    else:
        rng = np.random.default_rng(0)
        params["classifier"] = {
            "kernel": rng.normal(0, 0.02, (config.hidden_size, config.num_labels)).astype(np.float32),
            "bias": np.zeros((config.num_labels,), dtype=np.float32),
        }
    return {"params": params}


def init_params(config: BertConfig, rng: Optional[jax.Array] = None, seq_len: int = 128) -> Any:
    """Random-init parameters for a BertForSequenceClassification."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    model = BertForSequenceClassification(config)
    dummy = jnp.zeros((1, seq_len), dtype=jnp.int32)
    return model.init({"params": rng}, dummy, deterministic=True)
