"""Shared machinery for rule-based parameter sharding tables.

Each model family (BERT encoder, GPT decoder) declares only its ``spec_for`` rule
function; the path flattening / key normalization / tree reconstruction live here so
a fix for new jax key types lands once for every family.
"""

from typing import Any, Callable, Optional, Tuple

import jax


def shard_by_rules(
    params: Any,
    spec_for: Callable[[Tuple[str, ...], Any], Any],
    is_leaf: Optional[Callable[[Any], bool]] = None,
) -> Any:
    """Apply ``spec_for((path parts), leaf) -> PartitionSpec`` over a parameter tree.

    ``is_leaf`` stops flattening at composite leaves (e.g. ``QuantizedArray``
    nodes) so ``spec_for`` sees the whole node and can return a matching
    composite spec node instead of per-child specs."""
    flat = jax.tree_util.tree_flatten_with_path(params, is_leaf=is_leaf)[0]
    treedef = jax.tree_util.tree_structure(params, is_leaf=is_leaf)
    specs = [
        spec_for(tuple(getattr(k, "key", getattr(k, "idx", k)) for k in path), leaf)
        for path, leaf in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, specs)


def place_by_specs(params: Any, mesh: Any, spec_tree: Any) -> Any:
    """Lay a parameter tree onto ``mesh`` per a matching ``PartitionSpec`` tree.

    The serving-side counterpart of the trainer's ``jit(..., out_shardings=...)``
    layout: parameters arrive as host (or single-device) arrays and are committed
    to the mesh in one transfer, so the resident executables compile against
    already-sharded weights instead of replicating them per call.
    """
    from unionml_tpu.parallel.mesh import named_sharding_tree

    return jax.device_put(params, named_sharding_tree(mesh, spec_tree))
