"""GPT-style causal decoder (flax) with KV-cache generation.

Completes the model-family coverage next to the BERT encoder: pre-LN transformer
decoder blocks over the framework's causal flash attention for training, and an
explicit functional KV cache for O(1)-per-token greedy/temperature decoding under
``lax.scan`` (static shapes; the cache is a pytree argument, not module state, so the
whole generate loop jit-compiles). Prefill is chunked: one forward over the whole
prompt fills every layer's cache before the decode scan starts.

TPU-first choices: bfloat16 compute / f32 params, rotary-free learned positions (the
GPT-2 recipe), logits in f32, weight tying between embedding and LM head.
"""

import dataclasses
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from unionml_tpu.models.moe import MoEMlp
from unionml_tpu.ops.attention import attention, xla_attention
from unionml_tpu.ops.paged_attention import paged_attention


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_position_embeddings: int = 1024
    layer_norm_eps: float = 1e-5
    dropout: float = 0.1
    dtype: Any = jnp.bfloat16
    #: "auto" | "xla" | "pallas" | "ring" | "ulysses" — the last two are the
    #: sequence-parallel long-context TRAINING paths and require ``sp_mesh``
    #: (generation/KV-cache paths fall back to per-token attention)
    attention_impl: str = "auto"
    #: paged DECODE attention backend ("auto" | "pallas" | "xla"): the fused
    #: dequant-attend kernel vs the gather-dequant reference — see
    #: :mod:`unionml_tpu.ops.paged_attention`. "auto" = pallas on TPU
    #: (measured verdicts override per shape class), XLA elsewhere.
    paged_attn_impl: str = "auto"
    #: mesh carrying a "sequence" axis for ring/ulysses attention
    sp_mesh: Any = None
    #: remat (jax.checkpoint) decoder blocks during TRAINING forwards: activations
    #: recompute in the backward instead of living in HBM — the standard lever for
    #: bigger batches/longer sequences (mirrors BertConfig.remat)
    remat: bool = False
    #: sparse (mixture-of-experts) variant: every Nth block swaps its dense MLP for
    #: a routed :class:`unionml_tpu.models.moe.MoEMlp` (0 = fully dense). Router
    #: aux losses sow under "intermediates" — fold them into the training loss with
    #: :func:`unionml_tpu.models.moe.collect_aux_losses`.
    moe_every: int = 0
    num_experts: int = 8
    moe_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_router_noise: float = 0.0
    #: "gshard" (default) or "a2a" — see :class:`unionml_tpu.models.moe.MoEMlp`.
    #: "a2a" needs ``ep_mesh`` (an "expert" axis, optionally "data"): tokens are
    #: sharded and only routed tokens move, via explicit all-to-alls over ICI.
    moe_dispatch: str = "gshard"
    #: mesh for expert-parallel MoE dispatch (required by moe_dispatch="a2a")
    ep_mesh: Any = None

    @classmethod
    def tiny(cls, **overrides) -> "GPTConfig":
        defaults = dict(
            vocab_size=512, hidden_size=64, num_layers=2, num_heads=4, max_position_embeddings=128
        )
        defaults.update(overrides)
        return cls(**defaults)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def _paged_append_quantized(pool_q, pool_scale, dst, off, vals):
    """(jit-traceable) Single-token decode append into an int8 pool tail block.

    ``dst`` (batch,) pool block per row, ``off`` (batch,) offset inside it,
    ``vals`` (batch, heads, head_dim) the new token's K or V. Monotone-scale
    read-modify-write: a block's per-head scale resets on its first write
    (``off == 0``), afterwards only ever GROWS (``max(old, |token|/127)``), and
    the block's existing int8 content is rescaled only on an actual growth
    event — when the scale is unchanged the ratio is exactly 1.0 and the
    rescale is a bit-exact no-op, so rounding error does not compound across
    appends. Offsets past the write point are zeroed, scrubbing whatever a
    previous owner left in a reused block. Rows retired to the scratch block
    carry sentinel positions with ``off == 0`` (see the paged contract), so
    their collisions write self-consistent garbage to scratch only.
    """
    bs = pool_q.shape[2]
    old_q = pool_q[dst].astype(jnp.float32)  # (batch, heads, bs, hd)
    old_scale = pool_scale[dst]  # (batch, heads, 1, 1)
    vals32 = vals.astype(jnp.float32)[:, :, None, :]  # (batch, heads, 1, hd)
    tok_scale = jnp.max(jnp.abs(vals32), axis=-1, keepdims=True) / 127.0
    fresh = (off == 0)[:, None, None, None]
    eff_old = jnp.where(fresh, 0.0, old_scale)
    new_scale = jnp.maximum(eff_old, tok_scale)
    safe = jnp.where(new_scale > 0, new_scale, 1.0)
    rescaled = jnp.round(old_q * (eff_old / safe))
    tok_q = jnp.round(vals32 / safe)
    slot_idx = jnp.arange(bs)[None, None, :, None]
    off_b = off[:, None, None, None]
    new_q = jnp.where(slot_idx < off_b, rescaled, jnp.where(slot_idx == off_b, tok_q, 0.0))
    new_q = jnp.clip(new_q, -127, 127).astype(jnp.int8)
    return pool_q.at[dst].set(new_q), pool_scale.at[dst].set(new_scale)


def _paged_chunk_quantized(pool_q, pool_scale, table_row, position, vals):
    """(jit-traceable) Batch-1 chunk prefill into an int8 pool.

    ``vals`` (heads, seq, head_dim) is the chunk's K or V for positions
    ``[position, position + seq)``; ``table_row`` (width,) maps logical blocks
    to pool blocks. Touches only the ``ceil(seq/bs) + 1`` blocks the chunk can
    reach from ``position // bs`` (a straddling chunk spans one extra) — blocks
    BEFORE the write range are never read or written, which is what keeps a
    spliced shared prefix intact. The same monotone-scale discipline as the
    decode append applies: the first block may be mid-block (fresh only when
    the chunk starts at its offset 0), later blocks are fresh by construction.
    Logical blocks past the row's table width clamp to the trailing scratch
    column. Positions past the chunk's end are zeroed (stale-content scrub).
    """
    heads, seq, head_dim = vals.shape
    bs = pool_q.shape[2]
    width = table_row.shape[0]
    nb = -(-seq // bs) + 1  # static: touched blocks, incl. the straddle block
    position = jnp.asarray(position, jnp.int32)
    blk_idx = position // bs + jnp.arange(nb, dtype=jnp.int32)
    dst = jnp.take(table_row, jnp.clip(blk_idx, 0, width - 1))
    old_q = pool_q[dst].astype(jnp.float32)  # (nb, heads, bs, hd)
    old_scale = pool_scale[dst]  # (nb, heads, 1, 1)
    gpos = blk_idx[:, None] * bs + jnp.arange(bs)[None, :]  # (nb, bs) logical positions
    rel = gpos - position
    write = ((rel >= 0) & (rel < seq))[:, None, :, None]  # chunk content lands here
    live = (gpos < position + seq)[:, None, :, None]  # beyond: scrub to zero
    chunk = jnp.moveaxis(vals, 1, 0).astype(jnp.float32)  # (seq, heads, hd)
    take = jnp.take(chunk, jnp.clip(rel.reshape(-1), 0, seq - 1), axis=0)
    take = jnp.moveaxis(take.reshape(nb, bs, heads, head_dim), 2, 1)  # (nb, heads, bs, hd)
    fresh = (blk_idx * bs >= position)[:, None, None, None]
    eff_old = jnp.where(fresh, 0.0, old_scale)
    chunk_absmax = jnp.max(
        jnp.abs(jnp.where(write, take, 0.0)), axis=(2, 3), keepdims=True
    )
    new_scale = jnp.maximum(eff_old, chunk_absmax / 127.0)
    safe = jnp.where(new_scale > 0, new_scale, 1.0)
    rescaled = jnp.round(old_q * (eff_old / safe))
    new_q = jnp.where(write, jnp.round(take / safe), rescaled)
    new_q = jnp.clip(jnp.where(live, new_q, 0.0), -127, 127).astype(jnp.int8)
    return pool_q.at[dst].set(new_q), pool_scale.at[dst].set(new_scale)


def _paged_verify_chunk(cache, block_table, position, q, k, v, out_dtype, impl="auto"):
    """(jit-traceable) Speculative verify: attention context for ``S`` chunk
    tokens per row over the row's paged prefix, WITHOUT writing the pool.

    ``q``/``k``/``v`` are ``(batch, heads, S, head_dim)`` fresh projections for
    chunk tokens at per-row positions ``[position, position + S)``. The pool
    leaves in ``cache`` stay untouched — a rejected proposal must never perturb
    the pool, and in the int8 layout even an overwritten junk token would
    permanently inflate a block's monotone absmax scale. Numerics are
    BIT-IDENTICAL to feeding the chunk one token at a time through the decode
    append: each scan step mirrors the append arithmetic
    (:func:`_paged_append_quantized` / the fp ``.at[].set``) into a LOCAL
    gathered copy of the row's blocks — ``(batch, width, heads, bs, hd)``, the
    pool's own block layout — and attends through
    :func:`unionml_tpu.ops.paged_attention.paged_attention` over an identity
    table, so the verify step runs the SAME per-block arithmetic (same
    ``impl``) vanilla decode runs and accepted tokens score exactly as they
    would have under plain decoding; the engine's commit
    (:func:`paged_commit_chunk`) replays the same appends into the real pool.
    The attention rows serialize over ``S`` (tiny, bandwidth-equal to S vanilla
    steps); the win stays in the dense projections/MLP, which batch all S
    tokens per dispatch.
    """
    batch, heads, S, head_dim = q.shape
    block_size = cache["k"].shape[2]
    width = block_table.shape[1]
    capacity = width * block_size
    quantized = "k_scale" in cache
    b_idx = jnp.arange(batch)
    pos0 = position.astype(jnp.int32)
    # after the flatten below, row b's logical block w is local block b*width+w
    local_table = (b_idx[:, None] * width + jnp.arange(width)[None, :]).astype(jnp.int32)

    def local(leaf):
        # (batch, width, heads, bs, hd): the row's blocks, block structure kept
        return leaf[block_table]

    def flat(x):
        # the local state viewed as a (batch*width)-block pool for paged_attention
        return x.reshape((batch * width,) + x.shape[2:])

    if quantized:
        state = (
            local(cache["k"]).astype(jnp.float32), local(cache["k_scale"]),
            local(cache["v"]).astype(jnp.float32), local(cache["v_scale"]),
        )
    else:
        state = (local(cache["k"]), local(cache["v"]))

    def append_q(codes, scales, blk, off, vals):
        # _paged_append_quantized on the gathered layout, arithmetic bit for bit
        # (codes live as exact integers in f32, so round/clip/rescale match)
        old_q = codes[b_idx, blk]  # (batch, heads, bs, hd)
        old_scale = scales[b_idx, blk]
        vals32 = vals.astype(jnp.float32)[:, :, None, :]
        tok_scale = jnp.max(jnp.abs(vals32), axis=-1, keepdims=True) / 127.0
        fresh = (off == 0)[:, None, None, None]
        eff_old = jnp.where(fresh, 0.0, old_scale)
        new_scale = jnp.maximum(eff_old, tok_scale)
        safe = jnp.where(new_scale > 0, new_scale, 1.0)
        rescaled = jnp.round(old_q * (eff_old / safe))
        tok_q = jnp.round(vals32 / safe)
        slot_idx = jnp.arange(block_size)[None, None, :, None]
        off_b = off[:, None, None, None]
        new_q = jnp.where(slot_idx < off_b, rescaled, jnp.where(slot_idx == off_b, tok_q, 0.0))
        new_q = jnp.clip(new_q, -127, 127)
        return codes.at[b_idx, blk].set(new_q), scales.at[b_idx, blk].set(new_scale)

    def step(state, j):
        pos = jnp.clip(pos0 + j, 0, capacity - 1)
        blk, off = pos // block_size, pos % block_size
        kj = jax.lax.dynamic_index_in_dim(k, j, axis=2, keepdims=False)
        vj = jax.lax.dynamic_index_in_dim(v, j, axis=2, keepdims=False)
        qj = jax.lax.dynamic_index_in_dim(q, j, axis=2)  # (batch, heads, 1, hd)
        if quantized:
            kc, ks, vc, vs = state
            kc, ks = append_q(kc, ks, blk, off, kj)
            vc, vs = append_q(vc, vs, blk, off, vj)
            state = (kc, ks, vc, vs)
            ctx = paged_attention(
                qj, flat(kc), flat(vc), local_table, pos,
                k_scale=flat(ks), v_scale=flat(vs), out_dtype=out_dtype, impl=impl,
            )
        else:
            kb, vb = state
            kb = kb.at[b_idx, blk, :, off].set(kj.astype(kb.dtype))
            vb = vb.at[b_idx, blk, :, off].set(vj.astype(vb.dtype))
            state = (kb, vb)
            ctx = paged_attention(
                qj, flat(kb), flat(vb), local_table, pos, out_dtype=out_dtype, impl=impl,
            )
        return state, ctx[:, :, 0, :]

    _, rows = jax.lax.scan(step, state, jnp.arange(S, dtype=jnp.int32))
    return jnp.moveaxis(rows, 0, 2)  # (batch, heads, S, head_dim)


def paged_commit_chunk(layer_cache, block_table, position, counts, ck, cv):
    """(jit-traceable) Commit the first ``counts[row]`` verified chunk tokens
    of one layer into the paged pool as SEQUENTIAL single-token appends.

    ``ck``/``cv`` are the ``(batch, heads, S, head_dim)`` fresh K/V a verify
    pass stashed (see :func:`_paged_verify_chunk`); row positions start at
    ``position`` (the row's pre-round length). Chunk indices ``j >=
    counts[row]`` — rejected proposals and everything past a retirement — and
    fully inactive rows (``counts == 0``) route through the trailing scratch
    column, so the pool never learns a rejected token existed and the int8
    block-scale trajectory is exactly the one plain decoding would have
    produced for the accepted prefix.
    """
    quantized = "k_scale" in layer_cache
    block_size = layer_cache["k"].shape[2]
    width = block_table.shape[1]
    capacity = width * block_size
    sentinel = (width - 1) * block_size
    S = ck.shape[2]
    pos0 = position.astype(jnp.int32)

    def step(carry, j):
        live = j < counts
        pos = jnp.clip(jnp.where(live, pos0 + j, sentinel), 0, capacity - 1)
        blk, off = pos // block_size, pos % block_size
        dst = jnp.take_along_axis(block_table, blk[:, None], axis=1)[:, 0]
        kj = jax.lax.dynamic_index_in_dim(ck, j, axis=2, keepdims=False)
        vj = jax.lax.dynamic_index_in_dim(cv, j, axis=2, keepdims=False)
        if quantized:
            kq, ks, vq, vs = carry
            kq, ks = _paged_append_quantized(kq, ks, dst, off, kj)
            vq, vs = _paged_append_quantized(vq, vs, dst, off, vj)
            return (kq, ks, vq, vs), None
        kb, vb = carry
        kb = kb.at[dst, :, off, :].set(kj.astype(kb.dtype))
        vb = vb.at[dst, :, off, :].set(vj.astype(vb.dtype))
        return (kb, vb), None

    if quantized:
        carry = (
            layer_cache["k"], layer_cache["k_scale"],
            layer_cache["v"], layer_cache["v_scale"],
        )
        (kq, ks, vq, vs), _ = jax.lax.scan(step, carry, jnp.arange(S, dtype=jnp.int32))
        return {"k": kq, "k_scale": ks, "v": vq, "v_scale": vs}
    carry = (layer_cache["k"], layer_cache["v"])
    (kb, vb), _ = jax.lax.scan(step, carry, jnp.arange(S, dtype=jnp.int32))
    return {"k": kb, "v": vb}


class DecoderBlock(nn.Module):
    config: GPTConfig
    use_moe: bool = False

    @nn.compact
    def __call__(
        self,
        hidden,
        cache: Optional[Dict[str, jax.Array]],
        position,
        deterministic: bool,
        pad_offsets: Optional[jax.Array] = None,
        segment_ids: Optional[jax.Array] = None,
        block_table: Optional[jax.Array] = None,
    ):
        """Full-sequence (cache=None) or single-token incremental (cache given) step.

        Incremental contract: ``hidden`` is (batch, 1, d); ``cache`` holds
        ``{"k","v"}`` of shape (batch, heads, max_len, head_dim) plus the write
        ``position`` — a scalar (all rows at the same decode step) or a (batch,)
        int vector (continuous batching: each row at its OWN step, writing its own
        cache column; requires seq == 1). ``pad_offsets`` is a (batch,) count of
        LEFT-pad tokens per row (ragged-prompt batching): key positions below a
        row's offset are masked for that row. ``segment_ids`` (batch, seq) selects
        packed-sequence training (cache=None only): causal attention additionally
        confined to same-segment tokens. Returns (hidden, new_cache).

        Paged contract (``block_table`` given): ``cache`` holds ``{"k","v"}`` pool
        leaves of shape (num_blocks, heads, block_size, head_dim) shared by every
        row, and ``block_table`` is an int32 (batch, width) map from a row's
        logical block index to its pool block. Token position ``p`` lives at
        block ``table[row, p // block_size]``, offset ``p % block_size``. Writes
        scatter into the tail block in place; reads gather the row's table —
        contiguous logical order, so the mask arithmetic is identical to the
        dense path and outputs match it bitwise (masked columns hit exp(-inf)=0
        exactly). The engine keeps the last table column pointed at a scratch
        block and encodes retired rows' positions past ``(width-1)*block_size``,
        so their unavoidable scatter lands in scratch, never in a reused block.
        """
        cfg = self.config
        batch, seq, _ = hidden.shape
        normed = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, name="attn_norm")(hidden)
        qkv = nn.Dense(3 * cfg.hidden_size, dtype=cfg.dtype, name="qkv")(normed)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        split = lambda x: x.reshape(batch, seq, cfg.num_heads, cfg.head_dim).transpose(0, 2, 1, 3)
        q, k, v = split(q), split(k), split(v)

        def pad_mask(k_positions):
            # (batch, 1, 1, Lk): keys in a row's left-pad region contribute nothing
            return (k_positions[None, :] >= pad_offsets[:, None])[:, None, None, :]

        if cache is None:
            if segment_ids is not None:
                if pad_offsets is not None or cfg.attention_impl in ("ring", "ulysses"):
                    raise ValueError(
                        "segment_ids (packed training) composes with neither pad_offsets "
                        "(left-padded ragged batches) nor sequence-parallel attention"
                    )
                context = attention(
                    q, k, v, segment_ids=segment_ids, causal=True, impl=cfg.attention_impl
                )
            elif cfg.attention_impl in ("ring", "ulysses"):
                # sequence-parallel long-context training: activations shard over
                # the mesh's "sequence" axis; causal masking is handled inside
                if pad_offsets is not None:
                    # silently dropping to dense attention would defeat the O(seq/N)
                    # memory the sp layout exists for (and GPT's LEFT padding does
                    # not map onto the kernels' right-padding kv_lens contract)
                    raise ValueError(
                        "ring/ulysses attention does not support pad_offsets (left-padded "
                        "ragged batches); train sequence-parallel configs on uniform-length "
                        "batches or use a dense attention_impl."
                    )
                from unionml_tpu.parallel import sp_attention

                context = sp_attention(q, k, v, cfg.sp_mesh, cfg.attention_impl, causal=True)
            elif pad_offsets is None:
                context = attention(q, k, v, causal=True, impl=cfg.attention_impl)
            else:
                # causal=True supplies the triangular part; only the pad mask is ours
                context = xla_attention(q, k, v, causal=True, mask=pad_mask(jnp.arange(seq)))
            new_cache = None
        elif block_table is not None:
            per_row = not isinstance(position, int) and jnp.ndim(position) == 1
            if pad_offsets is not None:
                raise ValueError("paged decode does not support pad_offsets (left-padded rows)")
            if per_row and seq != 1:
                # speculative verify: score S chunk tokens per row against the
                # row's paged prefix without writing the pool; the engine commits
                # accepted tokens afterwards (paged_commit_chunk) from the fresh
                # K/V stashed alongside the untouched pool leaves
                context = _paged_verify_chunk(
                    cache, block_table, position, q, k, v, cfg.dtype,
                    impl=cfg.paged_attn_impl,
                )
                new_cache = {**cache, "ck": k, "cv": v}
            else:
                block_size = cache["k"].shape[2]
                width = block_table.shape[1]
                capacity = width * block_size
                # an int8-quantized pool announces itself structurally: scale leaves
                # ride next to k/v (see init_block_pool), so skip-listed layers fall
                # through to the full-precision path with zero config plumbing
                quantized = "k_scale" in cache
                k_scale = v_scale = None
                if per_row:
                    # decode: each row appends one token into its own tail block
                    pos = jnp.clip(position.astype(jnp.int32), 0, capacity - 1)
                    blk, off = pos // block_size, pos % block_size
                    dst = jnp.take_along_axis(block_table, blk[:, None], axis=1)[:, 0]
                    if quantized:
                        k_cache, k_scale = _paged_append_quantized(
                            cache["k"], cache["k_scale"], dst, off, k[:, :, 0, :]
                        )
                        v_cache, v_scale = _paged_append_quantized(
                            cache["v"], cache["v_scale"], dst, off, v[:, :, 0, :]
                        )
                    else:
                        k_cache = cache["k"].at[dst, :, off, :].set(k[:, :, 0, :].astype(cache["k"].dtype))
                        v_cache = cache["v"].at[dst, :, off, :].set(v[:, :, 0, :].astype(cache["v"].dtype))
                else:
                    # chunked prefill through the table (batch=1): scatter the chunk's
                    # K/V at positions [position, position+seq) of row 0's blocks
                    if batch != 1:
                        raise ValueError("paged chunk prefill requires batch == 1")
                    if quantized:
                        k_cache, k_scale = _paged_chunk_quantized(
                            cache["k"], cache["k_scale"], block_table[0], position, k[0]
                        )
                        v_cache, v_scale = _paged_chunk_quantized(
                            cache["v"], cache["v_scale"], block_table[0], position, v[0]
                        )
                    else:
                        pos = jnp.clip((position + jnp.arange(seq)).astype(jnp.int32), 0, capacity - 1)
                        blk, off = pos // block_size, pos % block_size
                        dst = jnp.take(block_table[0], blk)
                        k_cache = cache["k"].at[dst, :, off, :].set(
                            jnp.moveaxis(k[0], 1, 0).astype(cache["k"].dtype)
                        )
                        v_cache = cache["v"].at[dst, :, off, :].set(
                            jnp.moveaxis(v[0], 1, 0).astype(cache["v"].dtype)
                        )

                # attend through the table: impl="xla" is the historical
                # gather-dequant-attend (bitwise-preserved in
                # ops.paged_attention.xla_paged_attention); "pallas"/"auto"-on-TPU
                # runs the fused kernel that reads int8 codes + scales straight
                # off the pool — no dense dequantized gather copy in HBM. The
                # positional mask is base-position arithmetic either way:
                # query token s of row b sits at base[b] + s.
                if per_row:
                    base = position.astype(jnp.int32)
                else:
                    base = jnp.reshape(jnp.asarray(position, jnp.int32), (1,))
                context = paged_attention(
                    q, k_cache, v_cache, block_table, base,
                    k_scale=k_scale, v_scale=v_scale,
                    out_dtype=cfg.dtype, impl=cfg.paged_attn_impl,
                )
                new_cache = {"k": k_cache, "v": v_cache}
                if quantized:
                    new_cache["k_scale"] = k_scale
                    new_cache["v_scale"] = v_scale
        else:
            per_row = not isinstance(position, int) and jnp.ndim(position) == 1
            if per_row and seq != 1:
                raise ValueError("per-row cache positions require single-token decode (seq=1)")
            if per_row:
                # continuous batching: each row writes its next token's K/V at its
                # own column (one scatter; out-of-range rows clamp to the last
                # column, which the engine only allows for finished slots)
                max_cache_len = cache["k"].shape[2]
                cols = jnp.clip(position.astype(jnp.int32), 0, max_cache_len - 1)
                rows = jnp.arange(batch)
                k_cache = cache["k"].at[rows, :, cols, :].set(k[:, :, 0, :].astype(cache["k"].dtype))
                v_cache = cache["v"].at[rows, :, cols, :].set(v[:, :, 0, :].astype(cache["v"].dtype))
            else:
                # write the new K/V block at `position`; works for single-token decode
                # (seq=1) AND chunked prefill (seq=prompt_len, position=0)
                k_cache = jax.lax.dynamic_update_slice(cache["k"], k.astype(cache["k"].dtype), (0, 0, position, 0))
                v_cache = jax.lax.dynamic_update_slice(cache["v"], v.astype(cache["v"].dtype), (0, 0, position, 0))
            if seq > 1 and isinstance(position, int) and position == 0 and pad_offsets is None:
                # start-of-sequence prefill: no earlier keys exist, so plain causal
                # attention over the chunk (the flash kernel on TPU) is exact — no
                # dense mask, no scoring against empty cache slots. Sequence-parallel
                # impls are a TRAINING layout; cache paths fall back to standard
                # (non-sequence-parallel) attention.
                impl = "auto" if cfg.attention_impl in ("ring", "ulysses") else cfg.attention_impl
                context = attention(q, k, v, causal=True, impl=impl)
            elif seq > 1 and isinstance(position, int) and position == 0:
                # ragged prefill: attend over the chunk, causal + left-pad masked
                context = xla_attention(q, k, v, causal=True, mask=pad_mask(jnp.arange(seq)))
            else:
                # decode step / mid-sequence chunk: attend over the cache with a
                # causal mask built from the write position(s) — shared scalar, or
                # per-row columns (continuous batching: each row sees exactly its
                # own [0, position_r] prefix) — plus the left-pad mask when ragged
                k_pos = jnp.arange(k_cache.shape[2])
                if per_row:
                    q_pos = position[:, None] + jnp.arange(seq)[None, :]  # (batch, seq)
                    mask = (k_pos[None, None, :] <= q_pos[:, :, None])[:, None, :, :]
                else:
                    q_pos = position + jnp.arange(seq)
                    mask = (k_pos[None, :] <= q_pos[:, None])[None, None, :, :]
                if pad_offsets is not None:
                    mask = mask & pad_mask(k_pos)
                context = xla_attention(q, k_cache, v_cache, mask=mask)
            new_cache = {"k": k_cache, "v": v_cache}

        context = context.transpose(0, 2, 1, 3).reshape(batch, seq, cfg.hidden_size)
        attn_out = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="attn_out")(context)
        attn_out = nn.Dropout(cfg.dropout)(attn_out, deterministic=deterministic)
        hidden = hidden + attn_out

        normed = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, name="mlp_norm")(hidden)
        if self.use_moe:
            # deterministic (eval/generate) disables the capacity drop: a trained,
            # imbalanced router must not silently zero overflow tokens at inference,
            # and capacity depends on the per-call token count, which differs
            # between prefill, decode steps, and full forwards
            down = MoEMlp(
                num_experts=cfg.num_experts,
                hidden_size=4 * cfg.hidden_size,
                k=cfg.moe_k,
                capacity_factor=cfg.moe_capacity_factor,
                router_noise=cfg.moe_router_noise,
                dispatch=cfg.moe_dispatch,
                mesh=cfg.ep_mesh,
                dtype=cfg.dtype,
                name="moe_mlp",
            )(normed, dropless=deterministic, deterministic=deterministic)
        else:
            up = nn.Dense(4 * cfg.hidden_size, dtype=cfg.dtype, name="mlp_up")(normed)
            up = nn.gelu(up, approximate=True)
            down = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlp_down")(up)
        down = nn.Dropout(cfg.dropout)(down, deterministic=deterministic)
        return hidden + down, new_cache


class GPTLMHeadModel(nn.Module):
    """Decoder LM: token+position embeddings, N blocks, tied LM head."""

    config: GPTConfig

    @nn.compact
    def __call__(
        self,
        input_ids,
        cache: Optional[Dict[str, Any]] = None,
        position: Optional[jax.Array] = None,
        deterministic: bool = True,
        pad_offsets: Optional[jax.Array] = None,
        segment_ids: Optional[jax.Array] = None,
    ):
        """``pad_offsets`` (batch,) enables ragged-prompt batching: rows are LEFT-
        padded, each row's position embeddings start at its first real token, and
        attention never sees a row's pad region. Requires ``deterministic=True`` on
        sparse configs: capacity-bounded expert dispatch has no row isolation (pad
        tokens would compete for expert capacity slots against real tokens).

        ``segment_ids`` (batch, seq) enables PACKED training (cache=None): several
        short sequences share a row (t5x convention: 0 = padding, positive ids =
        segments), attention is confined to same-segment tokens (flash-kernel
        blockwise masking — no dense (seq, seq) mask), and position embeddings
        restart at each segment start. See :func:`unionml_tpu.ops.packing.pack_sequences`.

        A ``cache`` carrying a ``"table"`` key selects PAGED decoding: the layer
        entries are shared block-pool leaves (see :func:`init_block_pool`) and
        ``cache["table"]`` is the int32 (batch, width) block table every layer
        reads/writes through (one table, all layers — the pool is per-layer, the
        logical layout is not). The table rides through ``new_cache`` unchanged.
        """
        cfg = self.config
        if pad_offsets is not None and cfg.moe_every > 0 and not deterministic:
            raise ValueError(
                "pad_offsets with a MoE config requires deterministic=True: "
                "capacity-bounded expert dispatch lets pad tokens evict real tokens."
            )
        if segment_ids is not None and cache is not None:
            raise ValueError("segment_ids is a packed-TRAINING feature; decode caches are unpacked")
        batch, seq = input_ids.shape
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, name="wte")
        if segment_ids is not None:
            # positions restart at each segment boundary: subtract the running
            # index of the latest boundary (cummax of boundary positions)
            idx = jnp.arange(seq, dtype=jnp.int32)[None, :]
            ids = segment_ids.astype(jnp.int32)
            change = jnp.concatenate(
                [jnp.ones((batch, 1), bool), ids[:, 1:] != ids[:, :-1]], axis=1
            )
            seg_start = jax.lax.cummax(jnp.where(change, idx, 0), axis=1)
            positions = idx - seg_start
        elif cache is None:
            positions = jnp.arange(seq)[None, :]
        elif not isinstance(position, int) and jnp.ndim(position) == 1:
            # per-row decode positions (continuous batching)
            positions = (position[:, None] + jnp.arange(seq)[None, :]).astype(jnp.int32)
            positions = jnp.clip(positions, 0, cfg.max_position_embeddings - 1)
        else:
            positions = (position + jnp.arange(seq))[None, :].astype(jnp.int32)
        if pad_offsets is not None:
            # each row's first REAL token gets position 0 (pad slots clamp to 0 —
            # they are masked out of attention, the embedding just needs to be valid)
            positions = jnp.maximum(positions - pad_offsets[:, None].astype(jnp.int32), 0)
        hidden = embed(input_ids) + nn.Embed(
            cfg.max_position_embeddings, cfg.hidden_size, dtype=cfg.dtype, name="wpe"
        )(positions)
        hidden = nn.Dropout(cfg.dropout)(hidden, deterministic=deterministic)

        new_cache: Dict[str, Any] = {}
        block_table = cache.get("table") if cache is not None else None
        block_cls = DecoderBlock
        if cfg.remat and cache is None:
            # training forwards only: decode steps are tiny and cache-carrying
            # (deterministic is arg 4 counting self; it steers python control flow)
            block_cls = nn.remat(DecoderBlock, static_argnums=(4,))
        for i in range(cfg.num_layers):
            layer_cache = None if cache is None else cache[f"layer_{i}"]
            use_moe = cfg.moe_every > 0 and (i + 1) % cfg.moe_every == 0
            hidden, layer_cache = block_cls(cfg, use_moe=use_moe, name=f"layer_{i}")(
                hidden, layer_cache, position, deterministic, pad_offsets, segment_ids,
                block_table,
            )
            if layer_cache is not None:
                new_cache[f"layer_{i}"] = layer_cache
        if block_table is not None:
            new_cache["table"] = block_table

        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, name="final_norm")(hidden)
        # tied head with genuinely-f32 logits: Embed.attend would promote back to the
        # compute dtype (bf16), costing mantissa over a large vocab
        logits = jnp.dot(
            hidden.astype(jnp.float32),
            embed.embedding.astype(jnp.float32).T,
            preferred_element_type=jnp.float32,
        )
        return (logits, new_cache) if cache is not None else logits


def init_cache(
    config: GPTConfig, batch: int, max_len: Optional[int] = None, dtype: Any = None
) -> Dict[str, Any]:
    """Zeroed KV cache pytree for incremental decoding (config's compute dtype)."""
    max_len = max_len or config.max_position_embeddings
    dtype = dtype if dtype is not None else config.dtype
    shape = (batch, config.num_heads, max_len, config.head_dim)
    return {
        f"layer_{i}": {
            "k": jnp.zeros(shape, dtype=dtype),
            "v": jnp.zeros(shape, dtype=dtype),
        }
        for i in range(config.num_layers)
    }


def kv_cache_spec(config: GPTConfig, mesh_axis_names: Tuple[str, ...]) -> Any:
    """PartitionSpec for KV-cache leaves ``(batch, heads, max_len, head_dim)``.

    Serving shards the cache over attention HEADS on the ``tensor`` axis — the
    same split :func:`param_shardings` gives the fused qkv kernel, so each
    device holds exactly the K/V rows its attention shards produce and the
    decode step runs without resharding the cache. Heads stay replicated when
    the ``tensor`` axis is absent or does not divide the head count (a
    wrong-divisor shard would silently pad heads).
    """
    from jax.sharding import PartitionSpec as P

    from unionml_tpu.parallel.mesh import TENSOR_AXIS

    tensor = TENSOR_AXIS if TENSOR_AXIS in mesh_axis_names else None
    return P(None, tensor, None, None)


def init_block_pool(
    config: GPTConfig,
    num_blocks: int,
    block_size: int,
    dtype: Any = None,
    kv_quantize: Optional[str] = None,
    kv_quantize_skip_layers: Tuple[int, ...] = (),
) -> Dict[str, Any]:
    """Zeroed KV block pool for prefix caching: ``(num_blocks, heads, block_size,
    head_dim)`` per layer, the serving engine's reuse store for prompt-prefix KV.

    Heads sit on the same axis as :func:`init_cache` leaves, so the pool shards
    with the identical head-sharded spec (:func:`kv_block_spec`) and pool↔slot
    copies stay shard-local on a mesh (gather/scatter over the unsharded block
    axis only).

    ``kv_quantize="int8"`` stores K/V as symmetric int8 with per-block-per-head
    f32 scales resident alongside (``k_scale``/``v_scale``, shape ``(blocks,
    heads, 1, 1)`` — rank-4 so the one head-sharded spec covers every leaf and
    scale gathers stay shard-local). Layers listed in
    ``kv_quantize_skip_layers`` keep full-precision leaves (no scale entries) —
    the attention layer detects the mode structurally per layer, so mixed pools
    need no extra plumbing.
    """
    dtype = dtype if dtype is not None else config.dtype
    if kv_quantize not in (None, "int8"):
        raise ValueError(f"kv_quantize must be None or 'int8', got {kv_quantize!r}")
    skip = frozenset(int(i) for i in kv_quantize_skip_layers)
    shape = (num_blocks, config.num_heads, block_size, config.head_dim)
    scale_shape = (num_blocks, config.num_heads, 1, 1)
    pool: Dict[str, Any] = {}
    for i in range(config.num_layers):
        if kv_quantize == "int8" and i not in skip:
            pool[f"layer_{i}"] = {
                "k": jnp.zeros(shape, dtype=jnp.int8),
                "v": jnp.zeros(shape, dtype=jnp.int8),
                "k_scale": jnp.zeros(scale_shape, dtype=jnp.float32),
                "v_scale": jnp.zeros(scale_shape, dtype=jnp.float32),
            }
        else:
            pool[f"layer_{i}"] = {
                "k": jnp.zeros(shape, dtype=dtype),
                "v": jnp.zeros(shape, dtype=dtype),
            }
    return pool


def kv_block_bytes(
    config: GPTConfig,
    block_size: int,
    dtype: Any = None,
    kv_quantize: Optional[str] = None,
    kv_quantize_skip_layers: Tuple[int, ...] = (),
) -> int:
    """Bytes one pool block costs across ALL layers under the given layout —
    the unit of the equal-KV-byte A/B (`bench_serving --int8 ab`) and of pool
    sizing: ``pool_bytes = kv_block_bytes(...) * num_blocks``."""
    dtype = dtype if dtype is not None else config.dtype
    full_itemsize = jnp.dtype(dtype).itemsize
    per_head = block_size * config.head_dim
    skip = frozenset(int(i) for i in kv_quantize_skip_layers)
    total = 0
    for i in range(config.num_layers):
        if kv_quantize == "int8" and i not in skip:
            # int8 k + v, plus one f32 scale each per head
            total += config.num_heads * (2 * per_head * 1 + 2 * 4)
        else:
            total += config.num_heads * 2 * per_head * full_itemsize
    return total


def kv_pool_bytes(pool: Dict[str, Any], dense_dtype: Any) -> Tuple[int, int]:
    """(bytes_as_stored, bytes_if_full_precision) of a block pool, from shapes
    only (no device sync). The second number prices the same K/V positions at
    ``dense_dtype`` with no scale arrays — what the capacity doubling is
    measured against on dashboards."""
    stored = full = 0
    for layer in pool.values():
        for name, leaf in layer.items():
            stored += leaf.size * jnp.dtype(leaf.dtype).itemsize
            if not name.endswith("_scale"):
                full += leaf.size * jnp.dtype(dense_dtype).itemsize
    return stored, full


def init_slot_state(num_slots: int) -> Tuple[jax.Array, jax.Array]:
    """Zeroed device-resident slot lifecycle state for the serving engine.

    ``(active, remaining)`` — a bool activity mask and an int32 token budget per
    decode slot. The serving engine keeps these ON DEVICE and updates them
    *inside* the compiled decode step (:func:`advance_slot_state`), so a next
    step can be dispatched before the previous step's tokens are fetched: the
    host never has to round-trip slot lifecycle between device steps.
    """
    return (
        jnp.zeros((num_slots,), dtype=jnp.bool_),
        jnp.zeros((num_slots,), dtype=jnp.int32),
    )


def advance_slot_state(
    active: jax.Array,
    remaining: jax.Array,
    new_lens: jax.Array,
    tokens: jax.Array,
    max_len: int,
    eos_token_id: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """(jit-traceable) One decode step's slot retirement, the device-side rule.

    Mirrors the host's per-token accounting exactly — budget exhausted, cache
    room (``max_len - 1``) reached, or ``eos_token_id`` decoded — so a step
    program carrying ``(active, remaining)`` retires slots identically to a
    host replaying the fetched tokens. Inactive rows pass through unchanged.
    """
    new_remaining = jnp.where(active, remaining - 1, remaining)
    finished = (new_remaining <= 0) | (new_lens >= max_len - 1)
    if eos_token_id is not None:
        finished = finished | (tokens == eos_token_id)
    return active & ~finished, new_remaining


def block_table_width(max_len: int, block_size: int) -> int:
    """Columns in a slot's block-table row: ``ceil(max_len / block_size)`` data
    blocks plus one trailing scratch column (always mapped to the engine's
    scratch block) that absorbs the masked scatter of retired rows."""
    return -(-max_len // block_size) + 1


def init_block_tables(
    num_slots: int, max_len: int, block_size: int, scratch_id: int
) -> jax.Array:
    """int32 ``(num_slots, width)`` block tables, every entry on the scratch
    block: a fresh table maps nothing, and any write through it lands in
    scratch. See :func:`block_table_width` for the trailing scratch column."""
    width = block_table_width(max_len, block_size)
    return jnp.full((num_slots, width), scratch_id, dtype=jnp.int32)


def kv_block_spec(config: GPTConfig, mesh_axis_names: Tuple[str, ...]) -> Any:
    """PartitionSpec for KV block-pool leaves ``(blocks, heads, block_size,
    head_dim)``: heads on ``tensor``, exactly like :func:`kv_cache_spec`, so
    restoring a pool block into a slot's cache rows never reshards."""
    return kv_cache_spec(config, mesh_axis_names)


def gather_block_prefix(pool: Dict[str, Any], block_ids: jax.Array, pad_len: int) -> Dict[str, Any]:
    """(jit-traceable) Gather pool blocks into a batch-1 cache holding the prefix.

    ``block_ids`` is ``(n,)``; the result is a cache pytree of ``(1, heads,
    pad_len, head_dim)`` leaves whose first ``n * block_size`` columns are the
    gathered blocks in order (the rest zero, to be written by the suffix
    prefill). The gather indexes the unsharded block axis, so under a
    head-sharded mesh layout the copy is shard-local.
    """

    def gather(leaf):
        blocks = leaf[block_ids]  # (n, heads, block_size, head_dim)
        n, heads, block_size, head_dim = blocks.shape
        prefix = jnp.moveaxis(blocks, 0, 1).reshape(heads, n * block_size, head_dim)
        out = jnp.zeros((1, heads, pad_len, head_dim), leaf.dtype)
        return out.at[0, :, : n * block_size, :].set(prefix)

    return jax.tree_util.tree_map(gather, pool)


def slice_cache_blocks(
    cache: Dict[str, Any], row: jax.Array, start_block: jax.Array, num_blocks: int, block_size: int
) -> Dict[str, Any]:
    """(jit-traceable) Slice blocks ``[start, start + num_blocks)`` of one cache
    row into pool layout ``(num_blocks, heads, block_size, head_dim)`` per layer.

    ``row`` and ``start_block`` may be traced scalars (one compile per
    ``num_blocks`` count, not per slot or offset); the slice covers cache
    columns ``[start_block * block_size, (start_block + num_blocks) * block_size)``.
    """

    def take(leaf):
        r = leaf[row]  # (heads, max_len, head_dim)
        heads, _, head_dim = r.shape
        src = jax.lax.dynamic_slice_in_dim(
            r, start_block * block_size, num_blocks * block_size, axis=1
        )
        return jnp.moveaxis(src.reshape(heads, num_blocks, block_size, head_dim), 1, 0)

    return jax.tree_util.tree_map(take, cache)


def generate(
    model: GPTLMHeadModel,
    variables: Any,
    prompt_ids: jax.Array,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    rng: Optional[jax.Array] = None,
    max_len: Optional[int] = None,
    prompt_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Autoregressive decoding with a KV cache; one compiled scan, O(1) per token.

    ``temperature=0`` is greedy; otherwise samples with the given temperature,
    optionally filtered by ``top_k`` (0 = off) / ``top_p`` (1.0 = off) — same
    semantics as :mod:`unionml_tpu.ops.sampling` and the serving engine.
    ``prompt_mask`` (batch, prompt_len; 1 = real token) batches RAGGED prompts:
    rows must be LEFT-padded, so shorter prompts carry leading pad tokens that
    attention ignores and position embeddings skip — each row decodes exactly as it
    would alone. Returns (batch, prompt_len + max_new_tokens) token ids.
    """
    config = model.config
    batch, prompt_len = prompt_ids.shape
    total_len = prompt_len + max_new_tokens
    max_len = max_len or total_len
    # silent clamping here would corrupt the KV write slot and the position gather:
    # reject out-of-range requests loudly instead
    if total_len > max_len:
        raise ValueError(
            f"prompt_len + max_new_tokens ({total_len}) exceeds max_len ({max_len})"
        )
    if max_len > config.max_position_embeddings:
        raise ValueError(
            f"max_len ({max_len}) exceeds max_position_embeddings ({config.max_position_embeddings})"
        )
    from unionml_tpu.ops.sampling import validate_sampling

    temperature, top_k, top_p = validate_sampling(temperature, top_k, top_p)
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    pad_offsets = None
    if prompt_mask is not None:
        # left padding means each row's pad count is its number of leading zeros
        pad_offsets = (prompt_len - jnp.sum(prompt_mask.astype(jnp.int32), axis=1)).astype(jnp.int32)

    cache = init_cache(config, batch, max_len)

    # chunked prefill: one forward over the whole prompt fills every layer's cache
    logits, cache = model.apply(
        variables, prompt_ids, cache=cache, position=0, pad_offsets=pad_offsets
    )
    last_logits = logits[:, -1, :]

    def sample(logits, key):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        from unionml_tpu.ops.sampling import sample_logits

        rows = logits.shape[0]
        # statically-disabled filters pass None: sample_logits skips them, so
        # temperature-only sampling stays a plain categorical (no vocab sorts)
        return sample_logits(
            logits,
            key,
            jnp.full((rows,), temperature, jnp.float32),
            jnp.full((rows,), top_k, jnp.int32) if top_k > 0 else None,
            jnp.full((rows,), top_p, jnp.float32) if top_p < 1.0 else None,
        )

    def decode_step(carry, t):
        cache, logits, key = carry
        key, subkey = jax.random.split(key)
        token = sample(logits, subkey)
        new_logits, cache = model.apply(
            variables, token[:, None], cache=cache, position=prompt_len + t, pad_offsets=pad_offsets
        )
        return (cache, new_logits[:, -1, :], key), token

    (_, _, _), tokens = jax.lax.scan(
        decode_step, (cache, last_logits, rng), jnp.arange(max_new_tokens)
    )
    return jnp.concatenate([prompt_ids, tokens.T], axis=1)


def init_params(config: GPTConfig, rng: Optional[jax.Array] = None, seq_len: int = 32) -> Any:
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    model = GPTLMHeadModel(config)
    return model.init({"params": rng}, jnp.zeros((1, seq_len), dtype=jnp.int32), deterministic=True)


def lm_loss(
    logits: jax.Array,
    input_ids: jax.Array,
    mask: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Next-token cross-entropy: logits at t predict input_ids at t+1 (padding masked).

    With ``segment_ids`` (packed batches), cross-segment transitions are masked
    too: the last token of one packed sequence must not be trained to predict the
    first token of the next.
    """
    from unionml_tpu.ops.losses import cross_entropy_with_integer_labels

    shifted_logits = logits[:, :-1, :]
    targets = input_ids[:, 1:]
    weights = None if mask is None else mask[:, 1:]
    if segment_ids is not None:
        same_segment = (segment_ids[:, 1:] == segment_ids[:, :-1]) & (segment_ids[:, 1:] > 0)
        seg_weights = same_segment.astype(shifted_logits.dtype)
        weights = seg_weights if weights is None else weights * seg_weights
    return cross_entropy_with_integer_labels(shifted_logits, targets, weights)


def param_shardings(params: Any, mesh_axis_names: Tuple[str, ...] = ("data", "tensor")) -> Any:
    """PartitionSpec tree for the GPT parameter pytree (Megatron-style split).

    Mirrors :func:`unionml_tpu.models.bert.param_shardings` for the decoder family:

    - fused qkv kernel and MLP up-projection: shard the OUTPUT dim over ``tensor``
    - attention output and MLP down-projection: shard the INPUT dim over ``tensor``
    - token/position embeddings: shard the vocab/position dim over ``tensor``
    - MoE expert kernels (E, d, h)/(E, h, d): expert dim over ``expert`` when that
      axis exists, inner dims Megatron-split like the dense MLP
    - everything else replicated, or FSDP-sharded over ``fsdp`` when present
    - :class:`~unionml_tpu.ops.quant.QuantizedArray` leaves (weight-only int8):
      the int8 payload takes the kernel's spec; the scale keeps only the axes
      where it has extent (the channel axis), so it co-shards with the payload's
      output columns and the ``q * scale`` dequant runs without resharding

    XLA inserts the matching all-reduces over ICI; nothing else is needed.
    """
    from jax.sharding import PartitionSpec as P

    from unionml_tpu.ops.quant import QuantizedArray
    from unionml_tpu.parallel.ep import EXPERT_AXIS
    from unionml_tpu.parallel.mesh import FSDP_AXIS, TENSOR_AXIS

    tensor = TENSOR_AXIS if TENSOR_AXIS in mesh_axis_names else None
    fsdp = FSDP_AXIS if FSDP_AXIS in mesh_axis_names else None
    expert = EXPERT_AXIS if EXPERT_AXIS in mesh_axis_names else None

    def dense_spec(path_str: str, leaf) -> P:
        ndim = getattr(leaf, "ndim", 0)
        if "w_in" in path_str and ndim == 3:
            return P(expert, fsdp, tensor)
        if "w_out" in path_str and ndim == 3:
            return P(expert, tensor, fsdp)
        if ndim < 2:
            return P()
        if ("wte" in path_str or "wpe" in path_str) and "embedding" in path_str:
            return P(tensor, None)
        if ("qkv" in path_str or "mlp_up" in path_str) and path_str.endswith("kernel"):
            return P(fsdp, tensor)
        if ("attn_out" in path_str or "mlp_down" in path_str) and path_str.endswith("kernel"):
            return P(tensor, fsdp)
        if path_str.endswith("kernel"):
            return P(fsdp, None)
        return P()

    def spec_for(path: Tuple[str, ...], leaf):
        path_str = "/".join(str(p) for p in path)
        if isinstance(leaf, QuantizedArray):
            base = dense_spec(path_str, leaf.q)
            entries = tuple(base) + (None,) * (leaf.q.ndim - len(tuple(base)))
            scale_spec = P(
                *(
                    axis if i < leaf.scale.ndim and leaf.scale.shape[i] > 1 else None
                    for i, axis in enumerate(entries)
                )
            )
            # a spec-valued QuantizedArray node: same treedef (incl. dtype aux)
            # as the params node, so device_put/with_sharding_constraint zip them
            return QuantizedArray(q=base, scale=scale_spec, dtype=leaf.dtype)
        return dense_spec(path_str, leaf)

    from unionml_tpu.models._sharding import shard_by_rules

    return shard_by_rules(
        params, spec_for, is_leaf=lambda leaf: isinstance(leaf, QuantizedArray)
    )


def import_hf_weights(hf_state_dict: Dict[str, Any], config: GPTConfig) -> Dict[str, Any]:
    """Map a HuggingFace GPT-2 state dict (torch tensors or numpy) onto this module.

    Accepts ``GPT2Model`` or ``GPT2LMHeadModel`` state dicts. HF GPT-2 uses Conv1D
    projections whose weights are already (in, out) — no transpose, unlike torch
    Linear — and ties the LM head to ``wte``, matching this module's tied head.
    Mirrors :func:`unionml_tpu.models.bert.import_hf_weights` for the encoder family.
    """

    if config.moe_every > 0:
        raise ValueError(
            "import_hf_weights supports dense GPT-2 checkpoints only: a sparse config "
            "(moe_every > 0) has expert parameters with no HF counterpart."
        )

    def t(name: str) -> np.ndarray:
        value = hf_state_dict[name]
        if hasattr(value, "detach"):
            value = value.detach().cpu().numpy()
        return np.asarray(value)

    def conv1d(prefix: str) -> Dict[str, np.ndarray]:
        # HF Conv1D stores weight as (in_features, out_features): flax kernel layout
        return {"kernel": t(f"{prefix}.weight"), "bias": t(f"{prefix}.bias")}

    def norm(prefix: str) -> Dict[str, np.ndarray]:
        return {"scale": t(f"{prefix}.weight"), "bias": t(f"{prefix}.bias")}

    prefix = "transformer." if any(key.startswith("transformer.") for key in hf_state_dict) else ""
    params: Dict[str, Any] = {
        "wte": {"embedding": t(f"{prefix}wte.weight")},
        "wpe": {"embedding": t(f"{prefix}wpe.weight")},
        "final_norm": norm(f"{prefix}ln_f"),
    }
    for i in range(config.num_layers):
        hf_layer = f"{prefix}h.{i}"
        params[f"layer_{i}"] = {
            "attn_norm": norm(f"{hf_layer}.ln_1"),
            "qkv": conv1d(f"{hf_layer}.attn.c_attn"),
            "attn_out": conv1d(f"{hf_layer}.attn.c_proj"),
            "mlp_norm": norm(f"{hf_layer}.ln_2"),
            "mlp_up": conv1d(f"{hf_layer}.mlp.c_fc"),
            "mlp_down": conv1d(f"{hf_layer}.mlp.c_proj"),
        }
    return {"params": params}
