"""Speculative decoding: a small draft model proposes, the target verifies.

Autoregressive decoding runs one token per target-model forward; at decode
batch 1 the MXU is idle most of the step (weight streaming dominates).
Speculative decoding (Leviathan et al. 2023) restores arithmetic intensity the
TPU-friendly way: a cheap DRAFT model decodes ``gamma`` proposal tokens, then
the TARGET scores all of them in ONE chunked forward — a (gamma+1)-token matmul
instead of gamma+1 sequential single-token steps. Accepted prefixes advance the
sequence several tokens per target pass.

Guarantees:

- ``temperature=0`` (greedy): output is EXACTLY what target-only greedy decoding
  produces, token for token, for any draft model — the draft only affects speed.
  (Verification compares the target's argmax against each proposal and truncates
  at the first mismatch, emitting the target's own token there.)
- ``temperature>0``: the standard accept/residual rule — accept proposal ``x``
  with probability ``min(1, p_target(x)/p_draft(x))``, on rejection sample from
  the normalized positive residual ``max(p_target - p_draft, 0)`` — which makes
  each emitted token an exact sample from the target distribution.

Cache discipline (both models): after every round the KV caches are valid for
positions ``[0, n)`` where ``n`` counts tokens *fed*; the latest emitted token
is NOT yet fed (its K/V enters the cache at the start of the next round, as the
first element of the proposal/verification chunk). Rejected speculative columns
beyond ``n`` are never attended — the chunked decode mask is position-based
(``models/gpt.py`` DecoderBlock) — and are overwritten by later rounds.

Reference: the reference framework (unionai-oss/unionml) has no generation
machinery at all; this extends the TPU build's GPT family
(``models/gpt.py::generate``) with a lossless latency optimization.
"""

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["speculative_generate"]


def _prefill(model, variables, prompt_ids, max_len):
    from unionml_tpu.models.gpt import init_cache

    cache = init_cache(model.config, prompt_ids.shape[0], max_len)
    logits, cache = model.apply(variables, prompt_ids, cache=cache, position=0)
    return cache, logits[:, -1, :]


@functools.lru_cache(maxsize=16)
def _compiled_round_fns(target, draft, gamma: int, temperature: float):
    """Compiled (propose, verify, select) for one engine configuration.

    Cached at module level so repeated/serving calls reuse the XLA executables:
    defining these as per-call closures re-traced AND recompiled both programs on
    every generate call (ADVICE round-2). flax modules are frozen dataclasses
    (hashable, parameter-free metadata), so they key the cache directly; variables
    stay call arguments.
    """
    greedy = temperature <= 0.0

    def select(logits, key):
        if greedy:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(key, logits / temperature, axis=-1).astype(jnp.int32)

    @jax.jit
    def propose(draft_vars, cache, feed2, n_minus1, key):
        """Feed the last two committed tokens, then draft-decode gamma proposals,
        returning the logits row each was drawn from (the sampled accept rule
        needs the true proposal distribution).

        Why two: a full-accept round leaves the draft's cache missing the final
        proposal's K/V (verify feeds gamma+1 tokens to the target but propose fed
        only gamma to the draft); re-feeding the penultimate token backfills that
        hole with identical values in every other case (deterministic K/V of the
        same prefix), keeping the chunk shape static."""
        logits2, cache = draft.apply(draft_vars, feed2, cache=cache, position=n_minus1)
        key, sub = jax.random.split(key)
        first_logits = logits2[:, -1, :]
        p1 = select(first_logits, sub)

        def step(carry, _):
            cache, token, pos, key = carry
            key, sub = jax.random.split(key)
            logits, cache = draft.apply(draft_vars, token[:, None], cache=cache, position=pos)
            logits = logits[:, -1, :]
            nxt = select(logits, sub)
            return (cache, nxt, pos + 1, key), (nxt[0], logits[0])

        (cache, _, _, key), (rest, rest_rows) = jax.lax.scan(
            step, (cache, p1, n_minus1 + 2, key), None, length=gamma - 1
        )
        proposals = jnp.concatenate([p1, rest])
        logit_rows = jnp.concatenate([first_logits, rest_rows])
        return proposals, logit_rows, cache, key

    @jax.jit
    def verify(target_vars, cache, t_last, proposals, draft_logits, n, key):
        """One chunked target forward over [t_last, proposals]; returns the
        accepted count, the gamma+1 emission row, and the updated cache."""
        chunk = jnp.concatenate([t_last, proposals])[None, :]  # (1, gamma+1)
        logits, cache = target.apply(target_vars, chunk, cache=cache, position=n)
        rows = logits[0]  # (gamma+1, vocab): rows[i] follows chunk[i]
        if greedy:
            preds = jnp.argmax(rows, axis=-1).astype(jnp.int32)
            accept = jnp.cumprod((preds[:-1] == proposals).astype(jnp.int32))
            a = jnp.sum(accept)
            emitted = jnp.where(jnp.arange(gamma) < a, proposals, 0)
            closer = preds[a]  # correction on mismatch; bonus when a == gamma
        else:
            p_t = jax.nn.softmax(rows[:-1] / temperature, axis=-1)  # (gamma, vocab)
            p_d = jax.nn.softmax(draft_logits / temperature, axis=-1)
            idx = jnp.arange(gamma)
            pt_x = p_t[idx, proposals]
            pd_x = p_d[idx, proposals]
            key, k_accept, k_resid, k_bonus = jax.random.split(key, 4)
            u = jax.random.uniform(k_accept, (gamma,))
            ok = u * pd_x < pt_x  # u < p_t/p_d without the 0/0 division
            accept = jnp.cumprod(ok.astype(jnp.int32))
            a = jnp.sum(accept)
            emitted = jnp.where(jnp.arange(gamma) < a, proposals, 0)
            # rejection at position a: sample the normalized positive residual
            resid = jnp.maximum(p_t[jnp.minimum(a, gamma - 1)] - p_d[jnp.minimum(a, gamma - 1)], 0.0)
            resid = resid / jnp.maximum(jnp.sum(resid), 1e-20)
            resid_tok = jax.random.categorical(k_resid, jnp.log(resid + 1e-20)).astype(jnp.int32)
            bonus_tok = select(rows[-1][None, :], k_bonus)[0]
            closer = jnp.where(a == gamma, bonus_tok, resid_tok)
        emissions = jnp.concatenate([emitted, jnp.zeros((1,), jnp.int32)])
        emissions = emissions.at[a].set(closer)
        return a, emissions, cache, key

    return propose, verify, select


def speculative_generate(
    target: Any,
    target_variables: Any,
    draft: Any,
    draft_variables: Any,
    prompt_ids: jax.Array,
    max_new_tokens: int,
    *,
    gamma: int = 4,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    return_stats: bool = False,
) -> Any:
    """Decode ``max_new_tokens`` from ``target`` using ``draft`` speculation.

    :param target: the model whose output distribution is authoritative
        (:class:`~unionml_tpu.models.gpt.GPTLMHeadModel` or compatible).
    :param draft: a cheaper model sharing the target's vocabulary.
    :param prompt_ids: ``(1, prompt_len)`` int32 — batch 1 (rows would accept
        different prefix lengths and diverge positionally; batched speculation
        needs per-row chunk positions the cache layout doesn't support yet).
    :param gamma: proposal tokens per round; each round costs one draft scan of
        ``gamma`` steps plus ONE target forward over ``gamma+1`` tokens and
        advances 1..gamma+1 tokens.
    :param return_stats: also return ``{"rounds", "proposed", "accepted",
        "acceptance_rate"}`` (bonus/correction tokens are not counted as
        accepted proposals).
    :returns: ``(1, prompt_len + max_new_tokens)`` ids — same contract as
        :func:`unionml_tpu.models.gpt.generate` — or ``(ids, stats)``.
    """
    if prompt_ids.ndim != 2 or prompt_ids.shape[0] != 1:
        raise ValueError(f"speculative_generate expects (1, prompt_len) ids; got {prompt_ids.shape}")
    if gamma < 1:
        raise ValueError("gamma must be >= 1")
    if target.config.vocab_size != draft.config.vocab_size:
        raise ValueError(
            f"draft vocab ({draft.config.vocab_size}) must match target ({target.config.vocab_size})"
        )
    prompt_len = prompt_ids.shape[1]
    # speculation overshoots by up to gamma rejected columns; reserve the slack
    max_len = prompt_len + max_new_tokens + gamma + 1
    for cfg, name in ((target.config, "target"), (draft.config, "draft")):
        if max_len > cfg.max_position_embeddings:
            raise ValueError(
                f"prompt + max_new_tokens + gamma ({max_len}) exceeds the {name}'s "
                f"max_position_embeddings ({cfg.max_position_embeddings})"
            )
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    propose, verify, select = _compiled_round_fns(target, draft, gamma, float(temperature))

    # --- prefill both models, emit the first token from the target alone
    target_cache, t_logits = _prefill(target, target_variables, prompt_ids, max_len)
    draft_cache, _ = _prefill(draft, draft_variables, prompt_ids, max_len)
    rng, sub = jax.random.split(rng)
    t_last = select(t_logits, sub)  # (1,)

    # device_get (host-bound) instead of eager `arr[idx]` int() casts: an eager
    # getitem uploads its slice-start scalars, which the transfer-guard
    # steady-state regression disallows
    emitted = [int(np.asarray(jax.device_get(t_last))[0])]
    prev = int(np.asarray(jax.device_get(prompt_ids))[0, -1])  # penultimate committed token (see propose)
    n = prompt_len
    rounds = accepted_total = 0
    while len(emitted) < max_new_tokens:
        # EXPLICIT device_put for the per-round uploads: the round loop is the
        # speculative steady state, and implicit host→device transfers here are
        # exactly what the transfer-guard regression (and graftlint host-sync)
        # exist to catch — explicit placement keeps the guard green and the
        # intent visible
        feed2 = jax.device_put(np.asarray([[prev, emitted[-1]]], np.int32))
        # both positions uploaded explicitly (an eager `n_dev - 1` would
        # implicitly transfer the python 1 as a scalar constant)
        n_minus1, n_dev = jax.device_put((np.int32(n - 1), np.int32(n)))
        proposals, draft_logit_rows, draft_cache, rng = propose(
            draft_variables, draft_cache, feed2, n_minus1, rng
        )
        a, emissions, target_cache, rng = verify(
            target_variables, target_cache, t_last, proposals, draft_logit_rows, n_dev, rng
        )
        a = int(a)
        take = a + 1
        new_tokens = [int(t) for t in np.asarray(jax.device_get(emissions))[:take]]
        emitted.extend(new_tokens)
        prev = emitted[-2]
        t_last = jax.device_put(np.asarray([emitted[-1]], np.int32))
        n += take
        rounds += 1
        accepted_total += a

    out = jnp.concatenate(
        [prompt_ids, jax.device_put(np.asarray(emitted[:max_new_tokens], np.int32))[None, :]],
        axis=1,
    )
    if return_stats:
        proposed = rounds * gamma
        stats = {
            "rounds": rounds,
            "proposed": proposed,
            "accepted": accepted_total,
            "acceptance_rate": accepted_total / proposed if proposed else 0.0,
        }
        return out, stats
    return out
