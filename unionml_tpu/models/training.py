"""Compiled training loops for the model zoo: single-chip or mesh-sharded.

This is where the BASELINE "BERT-base fine-tune wall-clock" is won: one jit-compiled
train step (donated state, batch sharded over the mesh's data axis, params optionally
tensor/FSDP-sharded), a static-shape host batch iterator feeding it, step metrics
(loss, step time, tokens/s, achieved MFU), and orbax step checkpointing with
preemption-safe flush.
"""

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax.training import train_state
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from unionml_tpu._logging import logger
from unionml_tpu.ops.losses import cross_entropy_and_accuracy
from unionml_tpu.parallel.mesh import (
    DATA_AXIS,
    batch_axis_size,
    batch_sharding,
    wrapped_row_indices,
)
from unionml_tpu.utils import hard_sync


class TrainState(train_state.TrainState):
    """flax TrainState + dropout rng folding by step."""

    dropout_rng: jax.Array = None  # type: ignore[assignment]


def create_train_state(
    model: Any,
    params: Any,
    learning_rate: float = 2e-5,
    weight_decay: float = 0.01,
    warmup_steps: int = 0,
    total_steps: int = 10_000,
    max_grad_norm: float = 1.0,
    rng: Optional[jax.Array] = None,
    mu_dtype: Any = None,
) -> TrainState:
    """AdamW + linear warmup/decay + global-norm clipping (the BERT fine-tune recipe).

    ``mu_dtype`` (e.g. ``jnp.bfloat16``) stores adam's FIRST moment in reduced
    precision — the standard optimizer-HBM lever (halves mu traffic; the second
    moment stays f32 for numerical range). Measured by ``bench_mfu.py``'s
    ``*_bf16mu`` variants before being promoted to any default.
    """
    if warmup_steps > 0:
        schedule = optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=learning_rate,
            warmup_steps=warmup_steps,
            decay_steps=max(total_steps, warmup_steps + 1),
        )
    else:
        schedule = learning_rate
    tx = optax.chain(
        optax.clip_by_global_norm(max_grad_norm),
        optax.adamw(schedule, weight_decay=weight_decay, mu_dtype=mu_dtype),
    )
    variables = params if "params" in params else {"params": params}
    return TrainState.create(
        apply_fn=model.apply,
        params=variables["params"],
        tx=tx,
        dropout_rng=rng if rng is not None else jax.random.PRNGKey(0),
    )


def _accumulated_value_and_grad(loss_fn, params, batch, accum: int, dropout_rng, has_aux: bool):
    """Microbatched value-and-grad: mean loss/aux/grads over ``accum`` slices.

    ``loss_fn(params, microbatch, rng)`` runs per slice under ``lax.scan`` — peak
    activation memory is one microbatch's, which is the point (pairs with remat
    for memory-bound configs). Equal slice sizes make the mean-of-means exactly
    the full-batch mean; the optimizer step matches the full-batch step up to
    accumulation-order rounding (which adam's normalization amplifies for
    near-zero gradients).
    """

    def reshape(x):
        if x.shape[0] % accum:
            raise ValueError(
                f"grad_accum={accum} must divide the batch size ({x.shape[0]})"
            )
        return x.reshape(accum, x.shape[0] // accum, *x.shape[1:])

    micro = jax.tree_util.tree_map(reshape, batch)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=has_aux)

    first = jax.tree_util.tree_map(lambda x: x[0], micro)
    out_shapes = jax.eval_shape(grad_fn, params, first, dropout_rng)
    zeros = jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), out_shapes)

    def body(carry, slice_and_index):
        mb, index = slice_and_index
        out = grad_fn(params, mb, jax.random.fold_in(dropout_rng, index))
        return jax.tree_util.tree_map(jnp.add, carry, out), None

    total, _ = jax.lax.scan(body, zeros, (micro, jnp.arange(accum)))
    return jax.tree_util.tree_map(lambda x: x / accum, total)


def make_classifier_train_step(
    mesh: Optional[Mesh] = None,
    param_spec: Any = None,
    input_signature: Tuple[str, ...] = ("inputs",),
    light_metrics: bool = False,
    grad_accum: int = 1,
) -> Callable:
    """Build the compiled train step ``(state, batch) -> (state, metrics)``.

    ``batch`` is a dict with ``input_signature`` keys + ``"labels"``. With a mesh, the
    batch is sharded over the data axis and the state laid out by ``param_spec``
    (when None, leaves already committed to this mesh keep their layout and the
    rest replicate — see :func:`_wrap_step`); XLA inserts the grad all-reduce
    over ICI.
    ``light_metrics=True`` drops the ``grad_norm`` metric — in principle XLA CSEs it
    against the identical norm inside ``clip_by_global_norm``, and bench_mfu.py
    measures whether that holds on real hardware. ``grad_accum=N`` splits each
    batch into N sequential microbatches whose gradients average before the one
    optimizer step — same objective, one-Nth the activation memory.
    """
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")

    def train_step(state: TrainState, batch: Dict[str, jax.Array]):
        dropout_rng = jax.random.fold_in(state.dropout_rng, state.step)

        def loss_fn(params, mb, rng):
            logits = state.apply_fn(
                {"params": params},
                *[mb[k] for k in input_signature],
                deterministic=False,
                rngs={"dropout": rng},
            )
            return cross_entropy_and_accuracy(logits, mb["labels"])

        if grad_accum > 1:
            (loss, acc), grads = _accumulated_value_and_grad(
                loss_fn, state.params, batch, grad_accum, dropout_rng, has_aux=True
            )
        else:
            (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params, batch, dropout_rng
            )
        new_state = state.apply_gradients(grads=grads)
        metrics = {"loss": loss, "accuracy": acc}
        if not light_metrics:
            metrics["grad_norm"] = optax.global_norm(grads)
        return new_state, metrics

    return _wrap_step(train_step, mesh, param_spec)


def _wrap_step(train_step: Callable, mesh: Optional[Mesh], param_spec: Any) -> Callable:
    """jit a ``(state, batch) -> (state, metrics)`` step, mesh-sharded when given.

    With ``param_spec=None`` the state sharding is derived from the FIRST state the
    step sees: leaves already laid out on this mesh keep their sharding (e.g. params
    an internal ``shard_map`` committed to the expert axis during init — the
    a2a-MoE case), everything else replicates — the plain-DP default.
    """
    if mesh is None:
        return jax.jit(train_step, donate_argnums=(0,))
    if param_spec is not None:
        state_sharding = jax.tree_util.tree_map(
            lambda spec: NamedSharding(mesh, spec),
            param_spec,
            is_leaf=lambda x: isinstance(x, PartitionSpec),
        )
        return jax.jit(
            train_step,
            in_shardings=(state_sharding, batch_sharding(mesh)),
            donate_argnums=(0,),
        )

    # state sharding unspecified: committed leaves keep their layout (params an
    # internal shard_map bound to the expert axis during init — the a2a-MoE case,
    # whose layout also evolves onto the step's OUTPUT sharding after the first
    # donated call), uncommitted leaves replicate onto the mesh — the plain-DP
    # default an explicit replicated() used to force.
    jitted = jax.jit(
        train_step,
        in_shardings=(None, batch_sharding(mesh)),
        donate_argnums=(0,),
    )
    mesh_devices = set(mesh.devices.flat)

    def call(state, batch):
        # leaves committed to some OTHER device set (a single-device checkpoint
        # restore, an explicit device_put) would make jit raise an
        # incompatible-devices error against the mesh-sharded batch; reshard
        # them onto the mesh up front — the acceptance replicated() used to
        # provide. Leaves already on this mesh (or uncommitted) pass through.
        def place(leaf):
            sharding = getattr(leaf, "sharding", None)
            if sharding is None:  # numpy / scalars: jit replicates them itself
                return leaf
            if set(getattr(sharding, "device_set", mesh_devices)) == mesh_devices:
                return leaf
            return jax.device_put(leaf, NamedSharding(mesh, PartitionSpec()))

        return jitted(jax.tree_util.tree_map(place, state), batch)

    return call


def make_lm_train_step(
    mesh: Optional[Mesh] = None,
    param_spec: Any = None,
    packed: bool = False,
    light_metrics: bool = False,
    grad_accum: int = 1,
    moe_aux: bool = False,
) -> Callable:
    """Compiled causal-LM train step ``(state, batch) -> (state, metrics)``.

    ``batch`` carries ``"input_ids"`` plus, with ``packed=True``, the
    ``"segment_ids"`` from :func:`unionml_tpu.ops.packing.pack_sequences` — the
    model confines attention to same-segment tokens and restarts positions per
    segment, and the loss masks cross-segment transitions
    (:func:`unionml_tpu.models.gpt.lm_loss`). Unpacked batches may carry a
    ``"mask"`` (1 = real token) for plain right-padded LM training.
    ``grad_accum=N`` microbatches each step (see
    :func:`make_classifier_train_step`); note the packed per-row token counts
    vary, so accumulated loss weights microbatches equally, not per-token.
    ``moe_aux=True`` (sparse decoders) folds the sown router losses —
    z-loss + load-balancing (:func:`unionml_tpu.models.moe.collect_aux_losses`)
    — into the objective; without it a sparse model's router trains on the LM
    gradient alone and is free to collapse onto few experts.
    """
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
    from unionml_tpu.models.gpt import lm_loss
    from unionml_tpu.models.moe import collect_aux_losses

    def train_step(state: TrainState, batch: Dict[str, jax.Array]):
        dropout_rng = jax.random.fold_in(state.dropout_rng, state.step)

        def loss_fn(params, mb, rng):
            # strict lookup: a packed step fed a batch without segment ids must
            # fail loudly, not silently train across packed-sequence boundaries
            segment_ids = mb["segment_ids"] if packed else None
            if moe_aux:
                logits, sown = state.apply_fn(
                    {"params": params},
                    mb["input_ids"],
                    deterministic=False,
                    rngs={"dropout": rng},
                    segment_ids=segment_ids,
                    mutable=["intermediates"],
                )
                aux = collect_aux_losses(sown["intermediates"])
            else:
                logits = state.apply_fn(
                    {"params": params},
                    mb["input_ids"],
                    deterministic=False,
                    rngs={"dropout": rng},
                    segment_ids=segment_ids,
                )
                aux = 0.0
            return aux + lm_loss(
                logits, mb["input_ids"], mask=mb.get("mask"), segment_ids=segment_ids
            )

        if grad_accum > 1:
            loss, grads = _accumulated_value_and_grad(
                loss_fn, state.params, batch, grad_accum, dropout_rng, has_aux=False
            )
        else:
            loss, grads = jax.value_and_grad(loss_fn)(state.params, batch, dropout_rng)
        new_state = state.apply_gradients(grads=grads)
        metrics = {"loss": loss}
        if not light_metrics:
            metrics["grad_norm"] = optax.global_norm(grads)
        return new_state, metrics

    return _wrap_step(train_step, mesh, param_spec)


def make_lm_eval_step(packed: bool = False) -> Callable:
    """Compiled causal-LM eval step ``(state, batch) -> metrics``.

    Returns per-token ``loss`` and ``perplexity`` (exp of the masked mean
    next-token cross-entropy) over the batch's real transitions — the LM
    counterpart of :func:`make_classifier_eval_step`, sharing
    :func:`make_lm_train_step`'s batch contract (``input_ids`` plus
    ``segment_ids`` when packed / optional ``mask`` otherwise).
    """
    from unionml_tpu.models.gpt import lm_loss

    def eval_step(state: TrainState, batch: Dict[str, jax.Array]):
        segment_ids = batch["segment_ids"] if packed else None
        logits = state.apply_fn(
            {"params": state.params}, batch["input_ids"], deterministic=True,
            segment_ids=segment_ids,
        )
        loss = lm_loss(logits, batch["input_ids"], mask=batch.get("mask"), segment_ids=segment_ids)
        return {"loss": loss, "perplexity": jnp.exp(loss)}

    return jax.jit(eval_step)


def make_classifier_eval_step(input_signature: Tuple[str, ...] = ("inputs",)) -> Callable:
    def eval_step(state: TrainState, batch: Dict[str, jax.Array]):
        logits = state.apply_fn(
            {"params": state.params}, *[batch[k] for k in input_signature], deterministic=True
        )
        loss, acc = cross_entropy_and_accuracy(logits, batch["labels"])
        return {"loss": loss, "accuracy": acc}

    return jax.jit(eval_step)


@dataclass
class FitResult:
    state: TrainState
    metrics_history: list = field(default_factory=list)
    steps: int = 0
    wall_time_s: float = 0.0
    steps_per_s: float = 0.0
    examples_per_s: float = 0.0


def dict_batches(
    data: Dict[str, np.ndarray],
    batch_size: int,
    rng: Optional[np.random.Generator] = None,
    mesh: Optional[Mesh] = None,
    drop_remainder: bool = True,
) -> Iterable[Dict[str, np.ndarray]]:
    """Static-shape dict-batch iterator; optionally lays batches onto the mesh."""
    host = {k: np.asarray(v) for k, v in data.items()}
    n_rows = len(next(iter(host.values())))
    indices = np.arange(n_rows) if rng is None else rng.permutation(n_rows)
    end = (n_rows // batch_size) * batch_size if drop_remainder else n_rows
    if end == 0:
        end = n_rows
    sharding = batch_sharding(mesh) if mesh is not None else None
    axis_size = batch_axis_size(mesh) if mesh is not None else 1
    for start in range(0, end, batch_size):
        idx = indices[start : start + batch_size]
        if sharding is not None:
            wrap = wrapped_row_indices(len(idx), axis_size)
            if wrap is not None:
                idx = idx[wrap]
        batch = {k: v[idx] for k, v in host.items()}
        if sharding is not None:
            batch = {k: jax.device_put(v, sharding) for k, v in batch.items()}
        yield batch


def fit(
    state: TrainState,
    data: Dict[str, np.ndarray],
    *,
    batch_size: int,
    num_epochs: int = 1,
    num_steps: Optional[int] = None,
    mesh: Optional[Mesh] = None,
    param_spec: Any = None,
    input_signature: Tuple[str, ...] = ("inputs",),
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 100,
    log_every: int = 50,
    seed: int = 0,
    prefetch: bool = False,
    prefetch_convert: Optional[Dict[str, str]] = None,
    step_fn: Optional[Callable] = None,
    grad_accum: int = 1,
) -> FitResult:
    """Run the compiled train loop; resumes from ``checkpoint_dir`` when present.

    ``step_fn`` overrides the default classifier step with any compiled
    ``(state, batch) -> (state, metrics)`` — :func:`make_lm_train_step` and
    :func:`fit_lm` route packed-LM training through here, so every loop feature
    (checkpointing, prefetch, mesh batch layout, timing) is shared.

    ``prefetch=True`` gathers batches with the native threaded prefetcher
    (:class:`unionml_tpu.native.PrefetchLoader`), overlapping host-side batch assembly
    with device compute; falls back to Python batching when the native build is
    unavailable. ``prefetch_convert`` (e.g. ``{"inputs": "float32", "labels":
    "int32"}`` for raw pandas f64/i64 data, or ``{"inputs": "bfloat16"}`` for
    float32 sources) runs the per-array dtype conversion inside the native worker
    threads during the gather, so host data reaches the device in its compute
    dtype without Python ever paying element-wise conversion. Requires
    ``prefetch=True`` — silently skipping a requested conversion would be a
    correctness trap.
    """
    if step_fn is not None and grad_accum != 1:
        # silently ignoring a requested option is a correctness trap (same
        # stance as prefetch_convert below): accumulation belongs to the step
        # builder, so pass grad_accum to make_*_train_step instead
        raise ValueError("grad_accum applies to the built-in step; pass it to your step builder")
    if step_fn is None:
        step_fn = make_classifier_train_step(
            mesh=mesh, param_spec=param_spec, input_signature=input_signature,
            grad_accum=grad_accum,
        )

    if prefetch_convert and not prefetch:
        raise ValueError("prefetch_convert requires prefetch=True (conversion runs in the native gather workers)")

    prefetch_loader = None
    if prefetch:
        from unionml_tpu.native import PrefetchLoader

        prefetch_loader = PrefetchLoader(data, batch_size, convert=prefetch_convert)

    def batch_iterator(epoch_rng):
        if prefetch_loader is not None:
            sharding = batch_sharding(mesh) if mesh is not None else None
            axis = batch_axis_size(mesh) if mesh is not None else 1
            # copy=False feeds the loader's python-owned slot buffers straight to
            # device_put (zero host copies after the native gather) — safe ONLY for
            # real accelerators, where the transfer lands in separate device memory
            # and hard_sync fences it (block_until_ready is not a real barrier on
            # remote-TPU platforms — see utils.hard_sync). The CPU backend may ALIAS
            # an aligned host array instead of copying, so slot recycling would
            # corrupt "transferred" batches — keep the host copy there.
            zero_copy = jax.default_backend() != "cpu"

            def transfers():
                # deferred slot release lets batch N+1's host->device transfer fly
                # while step N computes: the slot recycles only after hard_sync
                # proves its transfer landed
                for views, release in prefetch_loader.epoch(
                    rng=epoch_rng, copy=not zero_copy, defer_release=True
                ):
                    if sharding is not None:
                        n = len(next(iter(views.values())))
                        wrap = wrapped_row_indices(n, axis)
                        if wrap is not None:  # ragged tail: wrap real rows to fit the mesh
                            views = {k: v[wrap] for k, v in views.items()}
                        yield {k: jax.device_put(v, sharding) for k, v in views.items()}, release
                    else:
                        yield {k: jax.device_put(v) for k, v in views.items()}, release

            pending = None
            for batch_and_release in transfers():
                if pending is not None:
                    batch, release = pending
                    hard_sync(batch)
                    release()
                    yield batch
                pending = batch_and_release
            if pending is not None:
                batch, release = pending
                hard_sync(batch)
                release()
                yield batch
            return
        yield from dict_batches(data, batch_size, rng=epoch_rng, mesh=mesh)

    checkpointer = None
    if checkpoint_dir is not None:
        from unionml_tpu.checkpoint import Checkpointer, install_preemption_handler

        checkpointer = Checkpointer(checkpoint_dir, save_interval_steps=checkpoint_every)
        install_preemption_handler(checkpointer)
        latest = checkpointer.latest_step()
        if latest is not None:
            logger.info("Resuming from checkpoint step %d", latest)
            state = checkpointer.restore(state)

    rng = np.random.default_rng(seed)
    history = []
    step = int(state.step)
    start_step = step
    # compile outside the timed region so wall-clock measures steady-state steps
    first_batch = next(iter(batch_iterator(rng)))
    state, metrics = step_fn(state, first_batch)
    float(metrics["loss"])  # host fetch = real barrier (see utils.hard_sync)
    step += 1

    t0 = time.perf_counter()
    done = False
    # an explicit step budget overrides the epoch count (loops data as needed)
    epochs = num_epochs if num_steps is None else max(num_epochs, 10**9)
    for epoch in range(epochs):
        for batch in batch_iterator(rng):
            state, metrics = step_fn(state, batch)
            step += 1
            if step % log_every == 0:
                metrics_host = {k: float(v) for k, v in metrics.items()}
                history.append({"step": step, **metrics_host})
                logger.info("step %d: %s", step, metrics_host)
            if checkpointer is not None:
                checkpointer.save(step, state)
            if num_steps is not None and step - start_step >= num_steps:
                done = True
                break
        if done:
            break
    float(metrics["loss"])  # host fetch = real barrier for the timed region
    wall = time.perf_counter() - t0
    if checkpointer is not None:
        checkpointer.flush()
    if prefetch_loader is not None:
        prefetch_loader.close()

    executed = step - start_step - 1  # first (compile) step excluded from the timing
    result = FitResult(
        state=state,
        metrics_history=history,
        steps=step,
        wall_time_s=wall,
        steps_per_s=executed / wall if wall > 0 else 0.0,
        examples_per_s=executed * batch_size / wall if wall > 0 else 0.0,
    )
    return result


def fit_lm(
    state: TrainState,
    sequences: Sequence[np.ndarray],
    *,
    seq_len: int,
    batch_size: int,
    pack: bool = True,
    max_segments_per_row: int = 0,
    num_epochs: int = 1,
    num_steps: Optional[int] = None,
    mesh: Optional[Mesh] = None,
    param_spec: Any = None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 100,
    log_every: int = 50,
    seed: int = 0,
    prefetch: bool = False,
    prefetch_convert: Optional[Dict[str, str]] = None,
    grad_accum: int = 1,
    moe_aux: bool = False,
) -> FitResult:
    """Causal-LM training over RAGGED token sequences through the shared fit loop.

    ``pack=True`` (the default) runs
    :func:`unionml_tpu.ops.packing.pack_sequences`: several short sequences share
    each fixed-shape row, segment ids confine attention and restart positions per
    segment, and cross-segment next-token transitions are masked out of the loss —
    so a packed batch trains exactly as its sequences would alone while wasting no
    MXU cycles on padding. ``pack=False`` right-pads one sequence per row with a
    loss mask (the naive layout, kept for ablations).

    This is the public packed-training entrypoint the reference cannot express at
    all: its training loop is opaque user code (reference ``unionml/model.py:560``
    runs the trainer inline), with no packing support anywhere.
    """
    from unionml_tpu.ops.packing import pack_sequences, packing_efficiency

    if pack:
        packed = pack_sequences(sequences, seq_len, max_segments_per_row=max_segments_per_row)
        data = {"input_ids": packed["input_ids"], "segment_ids": packed["segment_ids"]}
        logger.info(
            "packed %d sequences into %d rows of %d (efficiency %.1f%%, %d truncated)",
            len(sequences),
            packed["input_ids"].shape[0],
            seq_len,
            100.0 * packing_efficiency(packed["segment_ids"]),
            packed["truncated"],
        )
    else:
        input_ids = np.zeros((len(sequences), seq_len), dtype=np.int32)
        mask = np.zeros((len(sequences), seq_len), dtype=np.float32)
        truncated = 0
        for i, seq in enumerate(sequences):
            arr = np.asarray(seq).reshape(-1)[:seq_len]
            truncated += int(np.asarray(seq).size > seq_len)
            input_ids[i, : arr.size] = arr
            mask[i, : arr.size] = 1.0
        if truncated:
            logger.info("truncated %d sequences to seq_len=%d", truncated, seq_len)
        data = {"input_ids": input_ids, "mask": mask}

    step_fn = make_lm_train_step(
        mesh=mesh, param_spec=param_spec, packed=pack, grad_accum=grad_accum, moe_aux=moe_aux
    )
    return fit(
        state,
        data,
        batch_size=batch_size,
        num_epochs=num_epochs,
        num_steps=num_steps,
        mesh=mesh,
        param_spec=param_spec,
        checkpoint_dir=checkpoint_dir,
        checkpoint_every=checkpoint_every,
        log_every=log_every,
        seed=seed,
        prefetch=prefetch,
        prefetch_convert=prefetch_convert,
        step_fn=step_fn,
    )


def bert_flops_per_token(config: Any) -> float:
    """Approximate training FLOPs per token for MFU accounting (6 * params-ish)."""
    hidden, layers, inter = config.hidden_size, config.num_layers, config.intermediate_size
    per_layer = 4 * hidden * hidden + 2 * hidden * inter  # attn projections + mlp
    embed = 0  # lookup, negligible FLOPs
    fwd = layers * 2 * per_layer + embed  # 2 flops per MAC
    return 3.0 * fwd  # fwd + bwd ~ 3x forward
