"""Small dense models: MLP classifier (digits quickstart) and CNN (MNIST recipe).

These are the jax-native counterparts of the reference's sklearn/pytorch/keras digits
MLPs (``tests/integration/pytorch_app/quickstart.py``, ``keras_app/quickstart.py``):
same configs (hidden sizes, batch 512-style training) but compiled end-to-end.
"""

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLPClassifier(nn.Module):
    """Dense ReLU stack with a linear head; logits out."""

    hidden_sizes: Sequence[int] = (128,)
    num_classes: int = 10
    dtype: object = jnp.float32

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for i, size in enumerate(self.hidden_sizes):
            x = nn.Dense(size, dtype=self.dtype, name=f"dense_{i}")(x)
            x = nn.relu(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


class CNNClassifier(nn.Module):
    """Conv -> pool x2 -> dense head (the Keras-MNIST tutorial shape, compiled)."""

    num_classes: int = 10
    dtype: object = jnp.float32

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        if x.ndim == 3:
            x = x[..., None]
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype, name="conv_0")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), dtype=self.dtype, name="conv_1")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype, name="dense")(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
