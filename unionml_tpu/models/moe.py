"""Mixture-of-experts layer for the model zoo: router, losses, flax module.

Ties the expert-parallel dispatch primitives (:mod:`unionml_tpu.parallel.ep`) into a
usable network block. The reference has no math code at all (SURVEY.md: "no
CUDA/C++ anywhere"); this is part of the TPU-native model-family surface, alongside
BERT/GPT/MLP/CNN.

Components:

- :func:`router_z_loss` / :func:`load_balancing_loss` — the two standard router
  regularizers (ST-MoE z-loss keeps router logits small; the Switch/GShard balance
  loss pushes the token distribution toward uniform across experts).
- :class:`MoEMlp` — a drop-in replacement for a transformer MLP block: dense router,
  softmax gates, top-k capacity dispatch through
  :func:`unionml_tpu.parallel.ep.moe_apply_topk` (expert-sharded when a mesh with an
  ``"expert"`` axis is supplied, plain single-device dispatch otherwise). Aux losses
  are sown under ``intermediates/router_z_loss`` and
  ``intermediates/load_balancing_loss`` — collect with
  ``model.apply(..., mutable=["intermediates"])`` and add them to the training loss.
"""

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from unionml_tpu.parallel.ep import moe_apply_a2a, moe_apply_topk


def router_z_loss(router_logits: jax.Array) -> jax.Array:
    """ST-MoE z-loss: mean squared logsumexp of the router logits.

    Keeps router logits from drifting large (which makes the softmax saturate and
    the routing gradient vanish). Scale with ~1e-3 in the training loss.
    """
    return jnp.mean(jax.nn.logsumexp(router_logits, axis=-1) ** 2)


def load_balancing_loss(gates: jax.Array, expert_index: jax.Array, num_experts: int) -> jax.Array:
    """Switch-style load-balancing loss: ``E * sum_e f_e * P_e``.

    ``f_e`` is the fraction of tokens whose TOP choice is expert e; ``P_e`` the mean
    router probability for e. Equals 1.0 at perfect balance; grows as routing
    collapses onto few experts. Scale with ~1e-2 in the training loss.
    """
    one_hot = jax.nn.one_hot(expert_index, num_experts, dtype=gates.dtype)  # (t, e)
    tokens_per_expert = jnp.mean(one_hot, axis=0)
    prob_per_expert = jnp.mean(gates, axis=0)
    return num_experts * jnp.sum(tokens_per_expert * prob_per_expert)


class MoEMlp(nn.Module):
    """Transformer MLP block with top-k expert routing.

    Input/output: (..., d_model) — leading dims are flattened to a token axis for
    dispatch and restored after. Experts are two-layer MLPs (d_model -> hidden ->
    d_model, gelu). ``mesh`` (static) enables expert-axis sharding constraints; it
    must carry an ``"expert"`` axis dividing ``num_experts``.
    """

    num_experts: int
    hidden_size: int
    k: int = 2
    capacity_factor: float = 1.25
    mesh: Optional[Any] = None
    dtype: Any = jnp.float32
    #: Switch-style multiplicative router jitter: router INPUTS scale by
    #: U[1-noise, 1+noise] when a "dropout" rng stream is supplied (i.e. during
    #: training); eval/generate calls carry no rng and stay deterministic.
    router_noise: float = 0.0
    #: "gshard" routes via global one-hot dispatch einsums (XLA infers the
    #: collectives from sharding constraints); "a2a" shards the tokens and moves
    #: only routed tokens with explicit lax.all_to_all over the expert axis —
    #: O(local_tokens x k x capacity_factor) per device, the pod-scale layout.
    #: "a2a" requires ``mesh``; the dropless (inference) path is dense either way.
    dispatch: str = "gshard"
    #: token-sharding axis alongside "expert" for the a2a path (ignored when the
    #: mesh doesn't carry it)
    data_axis: str = "data"

    @nn.compact
    def __call__(self, x: jax.Array, dropless: bool = False, deterministic: bool = False) -> jax.Array:
        """``dropless=True`` disables the capacity drop (inference parity: a trained,
        imbalanced router must not silently zero overflow tokens during decode).
        ``deterministic=True`` additionally disables router jitter even when an rng
        stream is supplied — the same contract as ``nn.Dropout``."""
        d_model = x.shape[-1]
        tokens = x.reshape(-1, d_model)

        router_inputs = tokens.astype(jnp.float32)
        if self.router_noise > 0.0 and not deterministic and self.has_rng("dropout"):
            key = self.make_rng("dropout")
            router_inputs = router_inputs * jax.random.uniform(
                key, router_inputs.shape,
                minval=1.0 - self.router_noise, maxval=1.0 + self.router_noise,
            )
        router_logits = nn.Dense(self.num_experts, dtype=jnp.float32, name="router")(router_inputs)
        gates = jax.nn.softmax(router_logits, axis=-1)

        self.sow("intermediates", "router_z_loss", router_z_loss(router_logits))
        self.sow(
            "intermediates",
            "load_balancing_loss",
            load_balancing_loss(gates, jnp.argmax(router_logits, axis=-1), self.num_experts),
        )

        w_in = self.param(
            "w_in",
            nn.initializers.normal(0.02),
            (self.num_experts, d_model, self.hidden_size),
            self.dtype,
        )
        w_out = self.param(
            "w_out",
            nn.initializers.normal(0.02),
            (self.num_experts, self.hidden_size, d_model),
            self.dtype,
        )

        def expert_fn(params, toks):
            w1, w2 = params
            return jax.nn.gelu(toks @ w1) @ w2

        if self.dispatch not in ("gshard", "a2a"):
            raise ValueError(f"dispatch must be 'gshard' or 'a2a', got {self.dispatch!r}")
        # k=1 must NOT renormalize: top_gate/top_gate == 1.0 would erase the
        # Switch-style straight-through scaling (output scaled by the top-1 gate
        # value) — and with it the router's only gradient path through the task
        # loss. Same contract as ep.moe_apply_capacity, the top-1 wrapper.
        normalize = self.k > 1
        if self.dispatch == "a2a" and not dropless:
            if self.mesh is None or "expert" not in self.mesh.shape:
                raise ValueError("dispatch='a2a' requires a mesh with an 'expert' axis")
            out = moe_apply_a2a(
                expert_fn,
                (w_in, w_out),
                tokens.astype(self.dtype),
                gates.astype(self.dtype),
                self.mesh,
                k=self.k,
                capacity_factor=self.capacity_factor,
                normalize_gates=normalize,
                data_axis=self.data_axis,
            )
        else:
            out = moe_apply_topk(
                expert_fn,
                (w_in, w_out),
                tokens.astype(self.dtype),
                gates.astype(self.dtype),
                self.mesh,
                k=self.k,
                capacity_factor=None if dropless else self.capacity_factor,
                normalize_gates=normalize,
            )
        return out.reshape(x.shape).astype(x.dtype)


def collect_aux_losses(intermediates: Any, z_weight: float = 1e-3, balance_weight: float = 1e-2):
    """Sum the sown router losses from ``mutable=["intermediates"]`` output.

    Returns a scalar to ADD to the task loss: ``z_weight * sum(z losses) +
    balance_weight * sum(balance losses)`` across however many MoE layers sowed.
    """
    total = jnp.asarray(0.0, dtype=jnp.float32)
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(intermediates)[0]
    for path, leaf in leaves_with_paths:
        keys = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        if "router_z_loss" in keys:
            total = total + z_weight * jnp.sum(jnp.asarray(leaf, dtype=jnp.float32))
        elif "load_balancing_loss" in keys:
            total = total + balance_weight * jnp.sum(jnp.asarray(leaf, dtype=jnp.float32))
    return total
