"""Model zoo: jax-native models the framework owns end-to-end.

The reference owns no models (users bring sklearn/torch/keras callables); here the
digits/MNIST/BERT baseline configs ship as compiled flax modules with train steps,
shardings, and checkpointing (BASELINE.md configs 1-4).
"""

from unionml_tpu.models.bert import (
    BertConfig,
    BertForSequenceClassification,
    BertModel,
    import_hf_weights,
    init_params,
    param_shardings,
)
from unionml_tpu.models.mlp import CNNClassifier, MLPClassifier
from unionml_tpu.models.training import (
    FitResult,
    TrainState,
    create_train_state,
    dict_batches,
    fit,
    make_classifier_eval_step,
    make_classifier_train_step,
)

__all__ = [
    "BertConfig",
    "BertForSequenceClassification",
    "BertModel",
    "CNNClassifier",
    "FitResult",
    "MLPClassifier",
    "TrainState",
    "create_train_state",
    "dict_batches",
    "fit",
    "import_hf_weights",
    "init_params",
    "make_classifier_eval_step",
    "make_classifier_train_step",
    "param_shardings",
]
