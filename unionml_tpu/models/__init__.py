"""Model zoo: jax-native models the framework owns end-to-end.

The reference owns no models (users bring sklearn/torch/keras callables); here the
digits/MNIST/BERT baseline configs ship as compiled flax modules with train steps,
shardings, and checkpointing (BASELINE.md configs 1-4).
"""

from unionml_tpu.models.bert import (
    BertConfig,
    BertForSequenceClassification,
    BertModel,
    import_hf_weights,
    init_params,
    param_shardings,
)
# GPT helpers export under gpt-prefixed names: bare `generate`/`lm_loss` would
# collide with future decoder families the way init_params already collided with
# BERT's. Module-qualified access (models.gpt.generate) remains canonical.
from unionml_tpu.models.gpt import GPTConfig, GPTLMHeadModel
from unionml_tpu.models.gpt import generate as gpt_generate
from unionml_tpu.models.gpt import init_cache as init_gpt_cache
from unionml_tpu.models.gpt import import_hf_weights as import_hf_gpt_weights
from unionml_tpu.models.gpt import init_params as init_gpt_params
from unionml_tpu.models.gpt import lm_loss as gpt_lm_loss
from unionml_tpu.models.mlp import CNNClassifier, MLPClassifier
from unionml_tpu.models.moe import (
    MoEMlp,
    collect_aux_losses,
    load_balancing_loss,
    router_z_loss,
)
from unionml_tpu.models.training import (
    FitResult,
    TrainState,
    create_train_state,
    dict_batches,
    fit,
    fit_lm,
    make_classifier_eval_step,
    make_classifier_train_step,
    make_lm_eval_step,
    make_lm_train_step,
)

__all__ = [
    "BertConfig",
    "BertForSequenceClassification",
    "BertModel",
    "CNNClassifier",
    "FitResult",
    "MoEMlp",
    "import_hf_gpt_weights",
    "collect_aux_losses",
    "load_balancing_loss",
    "router_z_loss",
    "GPTConfig",
    "GPTLMHeadModel",
    "MLPClassifier",
    "fit_lm",
    "gpt_generate",
    "gpt_lm_loss",
    "make_lm_eval_step",
    "make_lm_train_step",
    "init_gpt_cache",
    "init_gpt_params",
    "TrainState",
    "create_train_state",
    "dict_batches",
    "fit",
    "import_hf_weights",
    "init_params",
    "make_classifier_eval_step",
    "make_classifier_train_step",
    "param_shardings",
]
