"""Schedules: cron-expression and fixed-rate job specs + an in-framework cron engine.

Reference parity: ``unionml/schedule.py:22-123`` — the ``Schedule`` dataclass and the
exactly-one-of cron/fixed-rate validation of ``create_scheduled_launchplan``. The
reference delegates actual firing to Flyte; here the execution backend owns a scheduler
loop (:mod:`unionml_tpu.backend`) driven by :func:`next_fire_time`, a self-contained
5-field cron evaluator (no croniter dependency).
"""

import calendar
from dataclasses import dataclass, field
from datetime import datetime, timedelta
from enum import Enum
from typing import List, Optional, Set, Union

from unionml_tpu.exceptions import ScheduleError


class ScheduleType(Enum):
    """Allowable schedule types (``schedule.py:12-19``)."""

    trainer = "trainer"
    predictor = "predictor"


#: croniter-style keyword aliases supported by the reference docs
CRON_ALIASES = {
    "@hourly": "0 * * * *",
    "@daily": "0 0 * * *",
    "@midnight": "0 0 * * *",
    "@weekly": "0 0 * * 0",
    "@monthly": "0 0 1 * *",
    "@yearly": "0 0 1 1 *",
    "@annually": "0 0 1 1 *",
}


@dataclass
class Schedule:
    """Spec for a recurring training or batch-prediction job (``schedule.py:22-64``)."""

    type: Union[str, ScheduleType]
    name: str
    expression: Optional[str] = None
    offset: Optional[str] = None
    fixed_rate: Optional[timedelta] = None
    time_arg: Optional[str] = None
    inputs: Optional[dict] = None
    activate_on_deploy: bool = True
    launchplan_kwargs: Optional[dict] = None

    def __post_init__(self):
        if isinstance(self.type, str):
            self.type = ScheduleType[self.type]

    def validate(self) -> None:
        """Exactly one of expression / fixed_rate must be given (``schedule.py:98-101``)."""
        if self.expression is not None and self.fixed_rate is not None:
            raise ScheduleError("You must specify exactly one of 'expression' or 'fixed_rate', not both.")
        if self.expression is None and self.fixed_rate is None:
            raise ScheduleError("You must specify exactly one of 'expression' or 'fixed_rate'.")
        if self.expression is not None:
            parse_cron(self.expression)  # raises on malformed expressions

    @property
    def workflow_kind(self) -> str:
        return "train" if self.type == ScheduleType.trainer else "predict"


def _parse_field(spec: str, lo: int, hi: int, names: Optional[dict] = None) -> Set[int]:
    """Parse one cron field: ``*``, ``*/n``, ``a-b``, ``a-b/n``, lists, names."""
    values: Set[int] = set()
    for part in spec.split(","):
        step = 1
        has_step = False
        if "/" in part:
            part, step_s = part.split("/", 1)
            has_step = True
            try:
                step = int(step_s)
            except ValueError as exc:
                raise ScheduleError(f"Invalid cron step {step_s!r}") from exc
            if step <= 0:
                raise ScheduleError(f"Cron step must be positive, got {step}")
        if names:
            part = names.get(part.lower(), part)
        if part in ("*", ""):
            start, end = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            if names:
                a, b = names.get(a.lower(), a), names.get(b.lower(), b)
            try:
                start, end = int(a), int(b)
            except ValueError as exc:
                raise ScheduleError(f"Invalid cron range {part!r}") from exc
        else:
            try:
                start = end = int(part)
            except ValueError as exc:
                raise ScheduleError(f"Invalid cron value {part!r}") from exc
            if has_step:
                # standard cron/croniter semantics: 'N/step' means the range N-hi
                # stepped, e.g. minute '5/15' fires at 5,20,35,50 — not just 5
                end = hi
        if not (lo <= start <= hi and lo <= end <= hi and start <= end):
            raise ScheduleError(f"Cron value {part!r} out of range [{lo}, {hi}]")
        values.update(range(start, end + 1, step))
    return values


_DOW_NAMES = {name.lower(): str(i) for i, name in enumerate(("sun", "mon", "tue", "wed", "thu", "fri", "sat"))}
_MONTH_NAMES = {name.lower(): str(i) for i, name in enumerate(calendar.month_abbr) if name}


class CronSpec:
    """A parsed 5-field cron expression."""

    def __init__(self, minutes: Set[int], hours: Set[int], days: Set[int], months: Set[int], weekdays: Set[int]):
        self.minutes, self.hours, self.days, self.months, self.weekdays = minutes, hours, days, months, weekdays

    def matches(self, ts: datetime) -> bool:
        # cron semantics: when both day-of-month and day-of-week are restricted, either may match
        cron_dow = (ts.weekday() + 1) % 7  # python Mon=0 -> cron Sun=0
        dom_restricted = self.days != set(range(1, 32))
        dow_restricted = self.weekdays != set(range(0, 7))
        if dom_restricted and dow_restricted:
            day_ok = ts.day in self.days or cron_dow in self.weekdays
        else:
            day_ok = ts.day in self.days and cron_dow in self.weekdays
        return ts.minute in self.minutes and ts.hour in self.hours and ts.month in self.months and day_ok


def parse_cron(expression: str) -> CronSpec:
    """Parse a cron expression or keyword alias into a :class:`CronSpec`."""
    expression = CRON_ALIASES.get(expression.strip(), expression.strip())
    parts = expression.split()
    if len(parts) != 5:
        raise ScheduleError(f"Cron expression must have 5 fields (or be a known alias); got {expression!r}")
    minute, hour, dom, month, dow = parts
    return CronSpec(
        minutes=_parse_field(minute, 0, 59),
        hours=_parse_field(hour, 0, 23),
        days=_parse_field(dom, 1, 31),
        months=_parse_field(month, 1, 12, names=_MONTH_NAMES),
        weekdays={v % 7 for v in _parse_field(dow, 0, 7, names=_DOW_NAMES)},
    )


def parse_iso_duration(value: str) -> timedelta:
    """Parse an ISO 8601 duration (``P[nD]T[nH][nM][nS]`` subset) into a timedelta.

    The reference's schedule ``offset`` field takes ISO 8601 durations
    (``unionml/schedule.py:39-44``); weeks/days/hours/minutes/seconds cover cron-offset
    use cases (months/years are ill-defined offsets and rejected).
    """
    import re

    match = re.fullmatch(
        r"P(?:(?P<weeks>\d+(?:\.\d+)?)W)?(?:(?P<days>\d+(?:\.\d+)?)D)?"
        r"(?:T(?:(?P<hours>\d+(?:\.\d+)?)H)?(?:(?P<minutes>\d+(?:\.\d+)?)M)?(?:(?P<seconds>\d+(?:\.\d+)?)S)?)?",
        value.strip(),
    )
    if not match or not any(match.groupdict().values()):
        raise ScheduleError(f"Invalid ISO 8601 duration {value!r} (months/years offsets are not supported)")
    parts = {k: float(v) for k, v in match.groupdict().items() if v}
    return timedelta(**parts)


def next_fire_time(schedule: Schedule, after: datetime) -> datetime:
    """Next time the schedule fires strictly after ``after`` (cron offset applied)."""
    schedule.validate()
    if schedule.fixed_rate is not None:
        return after + schedule.fixed_rate

    offset = parse_iso_duration(schedule.offset) if schedule.offset else timedelta()
    spec = parse_cron(schedule.expression)  # type: ignore[arg-type]
    # search in un-offset time so the returned fire time is cron-match + offset
    base = after - offset
    candidate = base.replace(second=0, microsecond=0) + timedelta(minutes=1)
    # scanning minute-by-minute is plenty for scheduler granularity; bound the search
    for _ in range(366 * 24 * 60):
        if spec.matches(candidate):
            return candidate + offset
        candidate += timedelta(minutes=1)
    raise ScheduleError(f"Cron expression {schedule.expression!r} never fires within a year")


def create_scheduled_job(
    workflow_name: str,
    name: str,
    *,
    expression: Optional[str] = None,
    offset: Optional[str] = None,
    fixed_rate: Optional[timedelta] = None,
    time_arg: Optional[str] = None,
    inputs: Optional[dict] = None,
    **launchplan_kwargs,
) -> Schedule:
    """Validate and build a deployable schedule (``schedule.py:67-123`` analogue).

    The reference returns a flytekit ``LaunchPlan``; here the backend consumes the
    :class:`Schedule` spec directly.
    """
    inputs = dict(inputs or {})
    if "fixed_inputs" in launchplan_kwargs:
        inputs.update(launchplan_kwargs.pop("fixed_inputs"))
    schedule = Schedule(
        type=ScheduleType.trainer if workflow_name.endswith(".train") else ScheduleType.predictor,
        name=name,
        expression=expression,
        offset=offset,
        fixed_rate=fixed_rate,
        time_arg=time_arg,
        inputs=inputs,
        launchplan_kwargs=launchplan_kwargs or None,
    )
    schedule.validate()
    return schedule
