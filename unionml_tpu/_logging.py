"""Structured logging for unionml_tpu.

Reference parity: ``unionml/_logging.py:1-7`` (a single stream logger). This version adds
per-stage timing support used by the stage runtime (SURVEY.md §5 "metrics/logging").
"""

import contextlib
import logging
import time
from typing import Iterator

logger = logging.getLogger("unionml_tpu")

if not logger.handlers:
    _handler = logging.StreamHandler()
    _handler.setFormatter(logging.Formatter("[%(name)s] %(asctime)s %(levelname)s: %(message)s"))
    logger.addHandler(_handler)
    logger.setLevel(logging.INFO)
    logger.propagate = False


@contextlib.contextmanager
def log_duration(event: str, level: int = logging.DEBUG) -> Iterator[None]:
    """Log wall-clock duration of a block, used for per-stage timing."""
    start = time.perf_counter()
    try:
        yield
    finally:
        logger.log(level, "%s took %.4fs", event, time.perf_counter() - start)
