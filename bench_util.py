"""Side-effect-free helpers shared by the bench scripts.

Deliberately free of module-level configuration: ``bench.py`` sets process-wide
logging levels and a persistent XLA compile-cache env var at import, which the
other bench scripts must NOT inherit just to reuse a path-policy function
(a warm compile cache silently flatters first-request/warmup timings).
"""

import os


def resolve_artifact_path(out_path: str, backend: str) -> str:
    """Where a bench run may write its committed artifact.

    One policy for every bench script: accelerator runs own the canonical
    artifact name; CPU smoke runs divert to a ``_cpu``-suffixed sibling
    (gitignored) so host timings can never overwrite the TPU measurements
    BASELINE.md quotes as the one source of truth.
    """
    if backend != "cpu":
        return out_path
    base, ext = os.path.splitext(out_path)
    return f"{base}_cpu{ext}"
